"""Program-IR verifier: pass framework + structured findings.

Reference parity: the role the protobuf IR's validation played in the
reference stack (framework.proto constraints enforced by OpDesc::Check /
the C++ executor's PADDLE_ENFORCE fences) — here as an explicit pass
framework over ``Program``/``Block``/``OpDesc`` that runs BEFORE lowering,
so a malformed program fails with the op index, op type, and variable
named instead of an XLA trace error deep inside jit.

The passes themselves live in :mod:`paddle_tpu.analysis.passes`; this
module owns the finding/report/error types, the pass registry, and the
driver (:func:`verify_program`).

Severity contract: ``error`` findings always fail verification;
``warning`` findings (dead ops/vars, inconclusive dtype inference) are
reported but non-fatal unless ``level="strict"`` promotes the dead-code
pass's warnings to errors. ``Executor.run`` drives this behind
``FLAGS_program_verify`` (off | on | strict), caching the verdict on the
Program per (version, feeds, fetches) so steady-state dispatch re-pays
nothing.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..errors import EnforceNotMet

__all__ = [
    "Finding", "VerifyError", "VerifyReport", "register_pass",
    "verifier_passes", "verify_program",
]


@dataclass
class Finding:
    """One verifier diagnosis, anchored to (block, op index, var)."""

    severity: str          # "error" | "warning"
    pass_name: str         # which verifier pass produced it
    message: str
    block_idx: int = 0
    op_index: Optional[int] = None   # index within its block's op list
    op_type: Optional[str] = None
    var: Optional[str] = None

    def where(self) -> str:
        loc = f"block {self.block_idx}"
        if self.op_index is not None:
            loc += f" op #{self.op_index}"
        if self.op_type:
            loc += f" <{self.op_type}>"
        return loc

    def __str__(self):
        var = f" var {self.var!r}" if self.var else ""
        return f"[{self.pass_name}] {self.where()}{var}: {self.message}"


@dataclass
class VerifyReport:
    """Outcome of one verification run over a Program."""

    findings: List[Finding] = field(default_factory=list)
    level: str = "on"

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if self._is_error(f)]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if not self._is_error(f)]

    def _is_error(self, f: Finding) -> bool:
        if f.severity == "error":
            return True
        # strict mode: dead code stops being advisory
        return self.level == "strict" and f.pass_name == "dead-code"

    @property
    def ok(self) -> bool:
        return not self.errors

    def raise_if_failed(self, program_repr=""):
        errs = self.errors
        if not errs:
            return self
        first = errs[0]
        more = f" (+{len(errs) - 1} more error(s))" if len(errs) > 1 else ""
        raise VerifyError(
            f"program verification failed{': ' + program_repr if program_repr else ''}"
            f"\n  {first}{more}",
            finding=first, report=self,
        )

    def __str__(self):
        if not self.findings:
            return "VerifyReport(clean)"
        lines = [f"VerifyReport({len(self.errors)} error(s), "
                 f"{len(self.warnings)} warning(s))"]
        lines += [f"  {f}" for f in self.findings]
        return "\n".join(lines)


class VerifyError(EnforceNotMet):
    """Structured verification failure (raised before any XLA lowering).

    Carries the first error :class:`Finding` — ``pass_name``,
    ``block_idx``, ``op_index``, ``op_type``, ``var`` — plus the full
    :class:`VerifyReport` for callers that want every diagnosis.
    """

    code = "PROGRAM_VERIFY"

    def __init__(self, message, finding: Finding = None,
                 report: VerifyReport = None):
        self.finding = finding
        self.report = report
        self.pass_name = finding.pass_name if finding else None
        self.block_idx = finding.block_idx if finding else None
        self.op_index = finding.op_index if finding else None
        self.op_type = finding.op_type if finding else None
        self.var = finding.var if finding else None
        op_context = None
        if finding is not None and finding.op_type:
            op_context = {"op_type": finding.op_type, "inputs": None}
        super().__init__(message, op_context=op_context)


# -- pass registry -----------------------------------------------------------

_PASSES: list = []  # [(name, fn)]


def register_pass(name: str):
    """Register a verifier pass: ``fn(ctx) -> None`` appending findings
    via ``ctx.error`` / ``ctx.warn``. Passes run in registration order;
    the structural pass runs first and gates the rest (walking a program
    whose block links are broken is not meaningful)."""

    def deco(fn):
        _PASSES.append((name, fn))
        return fn

    return deco


def verifier_passes() -> list:
    """Registered (name, fn) pairs, in run order."""
    from . import passes as _passes  # noqa: F401  (registers on import)

    return list(_PASSES)


class VerifyContext:
    """Everything a pass needs: the program plus the run's IO contract."""

    def __init__(self, program, feed_names=(), fetch_names=(), level="on"):
        self.program = program
        self.feed_names = frozenset(feed_names or ())
        self.fetch_names = tuple(fetch_names or ())
        self.level = level
        self.constants = frozenset(getattr(program, "_constants", {}) or ())
        # names resolvable without any op running: feeds, declared data
        # vars, persistables (the startup-scope promise), captured consts
        persist, data = set(), set()
        for blk in program.blocks:
            for name, var in blk.vars.items():
                if getattr(var, "persistable", False):
                    persist.add(name)
                if var._meta.get("is_data"):
                    data.add(name)
        self.persistables = frozenset(persist)
        self.data_vars = frozenset(data)
        self.findings: List[Finding] = []
        self.structure_ok = True  # set by the structural pass

    # -- finding emission ---------------------------------------------------
    def error(self, pass_name, message, block_idx=0, op_index=None,
              op_type=None, var=None):
        self.findings.append(Finding("error", pass_name, message, block_idx,
                                     op_index, op_type, var))

    def warn(self, pass_name, message, block_idx=0, op_index=None,
             op_type=None, var=None):
        self.findings.append(Finding("warning", pass_name, message,
                                     block_idx, op_index, op_type, var))

    # -- shared helpers -----------------------------------------------------
    def statically_defined(self, name) -> bool:
        return (name in self.feed_names or name in self.data_vars
                or name in self.persistables or name in self.constants)

    def resolve_var(self, block, name):
        """Block-scoped var lookup through parent links, or None."""
        try:
            return block.var(name)
        except KeyError:
            return None


def op_in_names(op):
    """Positional input names (mirrors static/executor.py op_in_names;
    duplicated here so the lint/verify layer imports no jax)."""
    slots = op.attrs.get("__in_slots__")
    if slots:
        return [n for s in slots for n in op.inputs.get(s, [])]
    return op.inputs.get("X", [])


def op_out_names(op):
    slots = op.attrs.get("__out_slots__")
    if slots:
        return [n for s in slots for n in op.outputs.get(s, [])]
    return op.outputs.get("Out", [])


def all_in_names(op):
    return [n for ns in op.inputs.values() for n in ns]


def all_out_names(op):
    return [n for ns in op.outputs.values() for n in ns]


def verify_program(program, feed_names=(), fetch_names=(),
                   level="on") -> VerifyReport:
    """Run every registered verifier pass over ``program``.

    Returns the :class:`VerifyReport` when verification passes (it may
    still carry warnings); raises :class:`VerifyError` naming the first
    offending (block, op index, op type, var) otherwise.
    """
    ctx = VerifyContext(program, feed_names, fetch_names, level)
    passes = verifier_passes()
    for name, fn in passes:
        fn(ctx)
        if name == "block-structure" and any(
                f.severity == "error" for f in ctx.findings):
            # broken block links: later passes would chase bad indices
            break
    report = VerifyReport(ctx.findings, level=level)
    report.raise_if_failed(program_repr=repr(program))
    return report
