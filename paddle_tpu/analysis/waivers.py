"""Graphlint waiver file: reviewed false positives, justified inline.

Format (``tools/graphlint_waivers.txt``): one waiver per line —

    <path> <rule> <scope>  # <justification>

- ``path``: repo-relative file path the finding is in (matched by
  normalized suffix, so absolute paths from the CLI still match);
- ``rule``: rule slug (``stale-flag-read``) or id (``GL001``), or ``*``;
- ``scope``: the finding's enclosing function name or dotted qualname
  (``Batcher._assemble``), or ``*`` for the whole file;
- the justification comment is REQUIRED — an unexplained waiver is
  itself a lint error, so the gate stays zero-by-default with every
  exception reviewable in one file.

Unused waivers are reported by the CLI so the file cannot silently rot.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = ["Waiver", "WaiverFormatError", "load_waivers", "match_waiver"]


class WaiverFormatError(ValueError):
    pass


@dataclass
class Waiver:
    path: str
    rule: str
    scope: str
    reason: str
    line_no: int = 0
    used: int = field(default=0)  # findings this waiver absorbed

    def __str__(self):
        return (f"{self.path} {self.rule} {self.scope}  # {self.reason}")


def load_waivers(path: str) -> List[Waiver]:
    """Parse a waiver file; raises :class:`WaiverFormatError` on a line
    without a justification (the gate must not accept bare waivers)."""
    waivers = []
    if not os.path.exists(path):
        return waivers
    with open(path, encoding="utf-8") as f:
        for i, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            body, sep, reason = line.partition("#")
            reason = reason.strip()
            if not sep or not reason:
                raise WaiverFormatError(
                    f"{path}:{i}: waiver without a justification comment "
                    f"('<path> <rule> <scope>  # why'): {line!r}")
            parts = body.split()
            if len(parts) != 3:
                raise WaiverFormatError(
                    f"{path}:{i}: expected '<path> <rule> <scope>  # why', "
                    f"got {line!r}")
            waivers.append(Waiver(parts[0], parts[1], parts[2], reason, i))
    return waivers


def _norm(p: str) -> str:
    return os.path.normpath(p).replace(os.sep, "/")


def match_waiver(waivers: List[Waiver], finding) -> Optional[Waiver]:
    """First waiver covering the finding (and mark it used), else None."""
    fpath = _norm(finding.path)
    for w in waivers:
        if w.rule not in ("*", finding.rule, finding.rule_id):
            continue
        wpath = _norm(w.path)
        if not (fpath == wpath or fpath.endswith("/" + wpath)):
            continue
        if w.scope != "*":
            qual = finding.func or "<module>"
            if not (qual == w.scope or qual.endswith("." + w.scope)
                    or w.scope in qual.split(".")):
                continue
        w.used += 1
        return w
    return None
