"""The verifier passes (registered into analysis.verifier's framework).

Run order matters: ``block-structure`` gates everything (walking broken
block links is meaningless), then ``def-before-use``, ``write-conflicts``,
``dtype-consistency``, and finally the advisory ``dead-code`` pass.

Executor-semantics notes the passes encode (static/executor.py):
- an op input resolves from, in order: a prior op's output in the same
  walk, the run's feed dict, a declared data var, a persistable var (the
  startup-scope promise), or a captured eager constant;
- sub-blocks run on a COPY of the enclosing env plus the formal names the
  parent control-flow op's attrs declare, so outer names are visible
  inside and sub-block writes (except threaded persistables) die with it;
- output lists may contain "" placeholders (grad ops) — never names;
- an op may write a var it also reads ONLY by declaring it in the
  ``__inplace__`` attr (optimizer updates, batch_norm's aliased running
  stats); undeclared read-write aliasing is exactly the conflict the
  executor's env overwrite would silently last-win.
"""
from __future__ import annotations

from .verifier import (
    all_in_names,
    all_out_names,
    op_in_names,
    op_out_names,
    register_pass,
)

_BLOCK_OPS = ("while", "cond", "scan")

# attr key -> is the value a list of names (else a single name)
_NAME_LIST_ATTRS = (
    "__cond_formals__", "__body_formals__", "__body_outs__",
    "__carry_formals__", "__seq_formals__", "__carry_outs__", "__y_outs__",
    "__true_outs__", "__false_outs__", "__inplace__",
)
_NAME_ATTRS = ("__cond_out__",)

# which sub-blocks + formal lists each control-flow op type declares
_SUBBLOCK_SPEC = {
    "while": (
        ("__cond_block__", ("__cond_formals__",)),
        ("__body_block__", ("__body_formals__",)),
    ),
    "cond": (
        ("__true_block__", ()),
        ("__false_block__", ()),
    ),
    "scan": (
        ("__body_block__", ("__carry_formals__", "__seq_formals__")),
    ),
}

_REQUIRED_ATTRS = {
    "while": ("__cond_block__", "__body_block__", "__cond_formals__",
              "__body_formals__", "__cond_out__", "__body_outs__",
              "__n_loop__"),
    "cond": ("__true_block__", "__false_block__", "__true_outs__",
             "__false_outs__"),
    "scan": ("__body_block__", "__carry_formals__", "__seq_formals__",
             "__carry_outs__", "__y_outs__", "__n_carry__", "__n_seq__"),
}


def _attr_names(op):
    """Every var name an op references through its control/alias attrs."""
    names = []
    for key in _NAME_LIST_ATTRS:
        v = op.attrs.get(key)
        if v:
            names.extend(n for n in v if n)
    for key in _NAME_ATTRS:
        v = op.attrs.get(key)
        if v:
            names.append(v)
    return names


def _parent_chain(program, block_idx):
    """Block indices from ``block_idx`` up to the root (cycle-safe)."""
    chain, seen = [], set()
    idx = block_idx
    while 0 <= idx < len(program.blocks) and idx not in seen:
        chain.append(idx)
        seen.add(idx)
        idx = program.blocks[idx].parent_idx
    return chain


# ---------------------------------------------------------------------------
# 1. block-structure: parent links, sub-block attrs, formal declarations
# ---------------------------------------------------------------------------

@register_pass("block-structure")
def _block_structure(ctx):
    program = ctx.program
    n_blocks = len(program.blocks)
    if n_blocks == 0:
        ctx.error("block-structure", "program has no blocks")
        return
    for pos, blk in enumerate(program.blocks):
        if blk.idx != pos:
            ctx.error("block-structure",
                      f"block at position {pos} carries idx {blk.idx}",
                      block_idx=pos)
        if pos == 0:
            if blk.parent_idx != -1:
                ctx.error("block-structure",
                          f"global block declares parent {blk.parent_idx} "
                          "(must be -1)", block_idx=0)
            continue
        if not (0 <= blk.parent_idx < n_blocks) or blk.parent_idx == pos:
            ctx.error("block-structure",
                      f"block {pos} has invalid parent_idx "
                      f"{blk.parent_idx}", block_idx=pos)
            continue
        chain = _parent_chain(program, pos)
        if chain[-1] != 0:
            ctx.error("block-structure",
                      f"block {pos}'s parent chain {chain} never reaches "
                      "the global block (cycle or dangling link)",
                      block_idx=pos)

    for blk in program.blocks:
        for i, op in enumerate(blk.ops):
            if op.type not in _BLOCK_OPS:
                continue
            missing = [a for a in _REQUIRED_ATTRS[op.type]
                       if a not in op.attrs]
            if missing:
                ctx.error("block-structure",
                          f"{op.type} op is missing control attrs "
                          f"{missing}", block_idx=blk.idx, op_index=i,
                          op_type=op.type)
                continue
            for bkey, fkeys in _SUBBLOCK_SPEC[op.type]:
                bidx = op.attrs[bkey]
                if not isinstance(bidx, int) or not (0 < bidx < n_blocks):
                    ctx.error("block-structure",
                              f"{bkey}={bidx!r} does not name a sub-block "
                              f"of this program ({n_blocks} blocks)",
                              block_idx=blk.idx, op_index=i,
                              op_type=op.type)
                    continue
                sub = program.blocks[bidx]
                if blk.idx not in _parent_chain(program, bidx):
                    ctx.error("block-structure",
                              f"sub-block {bidx}'s parent chain does not "
                              f"include block {blk.idx}; vars captured "
                              "across the block boundary cannot resolve",
                              block_idx=blk.idx, op_index=i,
                              op_type=op.type)
                for fkey in fkeys:
                    for formal in op.attrs.get(fkey, ()):
                        if formal not in sub.vars:
                            ctx.error(
                                "block-structure",
                                f"formal {formal!r} ({fkey}) is not "
                                f"declared in sub-block {bidx}",
                                block_idx=blk.idx, op_index=i,
                                op_type=op.type, var=formal)
            _check_block_op_arity(ctx, blk, i, op)


def _check_block_op_arity(ctx, blk, i, op):
    outs = [n for n in op_out_names(op) if n]
    if op.type == "while":
        n_loop = op.attrs["__n_loop__"]
        ins = op_in_names(op)
        sizes = {
            "__cond_formals__": len(op.attrs["__cond_formals__"]),
            "__body_formals__": len(op.attrs["__body_formals__"]),
            "__body_outs__": len(op.attrs["__body_outs__"]),
        }
        bad = {k: v for k, v in sizes.items() if v != n_loop}
        if bad or len(ins) < n_loop or len(outs) != n_loop:
            ctx.error("block-structure",
                      f"while op carry arity mismatch: __n_loop__={n_loop} "
                      f"but inputs={len(ins)} outputs={len(outs)} {sizes}",
                      block_idx=blk.idx, op_index=i, op_type=op.type)
    elif op.type == "cond":
        t, f = op.attrs["__true_outs__"], op.attrs["__false_outs__"]
        if len(t) != len(f) or len(outs) != len(t):
            ctx.error("block-structure",
                      f"cond op output arity mismatch: true={len(t)} "
                      f"false={len(f)} declared={len(outs)}",
                      block_idx=blk.idx, op_index=i, op_type=op.type)
    elif op.type == "scan":
        n_c = op.attrs["__n_carry__"]
        n_y = len(op.attrs["__y_outs__"])
        if (len(op.attrs["__carry_outs__"]) != n_c
                or len(op.attrs["__carry_formals__"]) != n_c
                or len(outs) != n_c + n_y):
            ctx.error("block-structure",
                      f"scan op carry/y arity mismatch: __n_carry__={n_c} "
                      f"__y_outs__={n_y} declared outputs={len(outs)}",
                      block_idx=blk.idx, op_index=i, op_type=op.type)


# ---------------------------------------------------------------------------
# 2. def-before-use: every input resolvable at the point its op runs
# ---------------------------------------------------------------------------

@register_pass("def-before-use")
def _def_before_use(ctx):
    program = ctx.program

    def walk(block_idx, defined, visiting):
        if block_idx in visiting:  # structural pass already flagged cycles
            return
        blk = program.blocks[block_idx]
        for i, op in enumerate(blk.ops):
            for n in all_in_names(op):
                if not n:
                    continue
                if n not in defined and not ctx.statically_defined(n):
                    ctx.error(
                        "def-before-use",
                        f"input {n!r} is not produced by any prior op and "
                        "is neither a feed/data var, a persistable "
                        "(startup-scope) var, nor a captured constant",
                        block_idx=blk.idx, op_index=i, op_type=op.type,
                        var=n)
            if op.type in _BLOCK_OPS:
                for bkey, fkeys in _SUBBLOCK_SPEC.get(op.type, ()):
                    bidx = op.attrs.get(bkey)
                    if isinstance(bidx, int) and 0 < bidx < len(program.blocks):
                        formals = [f for k in fkeys
                                   for f in op.attrs.get(k, ())]
                        walk(bidx, defined | set(formals),
                             visiting | {block_idx})
            for n in all_out_names(op):
                if n:
                    defined.add(n)
        return defined

    defined = walk(0, set(), frozenset()) or set()
    for n in ctx.fetch_names:
        if n not in defined and not ctx.statically_defined(n):
            ctx.error("def-before-use",
                      f"fetch target {n!r} is never produced by the "
                      "program (and is not a feed/persistable var)",
                      var=n)


# ---------------------------------------------------------------------------
# 3. write-conflicts: double writes + undeclared in-place aliasing
# ---------------------------------------------------------------------------

@register_pass("write-conflicts")
def _write_conflicts(ctx):
    for blk in ctx.program.blocks:
        writers: dict = {}  # name -> op index of first writer
        for i, op in enumerate(blk.ops):
            ins = set(n for n in all_in_names(op) if n)
            declared = set(op.attrs.get("__inplace__") or ())
            seen_here = set()
            for n in all_out_names(op):
                if not n:
                    continue
                if n in seen_here:
                    ctx.error("write-conflicts",
                              f"op writes {n!r} twice in one output list",
                              block_idx=blk.idx, op_index=i,
                              op_type=op.type, var=n)
                    continue
                seen_here.add(n)
                if n in ins and n not in declared:
                    ctx.error(
                        "write-conflicts",
                        f"op writes {n!r} which it also reads without "
                        "declaring the aliasing (add it to the op's "
                        "__inplace__ attr if the in-place update is "
                        "intended)",
                        block_idx=blk.idx, op_index=i, op_type=op.type,
                        var=n)
                prev = writers.get(n)
                if prev is not None:
                    # a persistable updated in place by every later writer
                    # is a legal sequential state chain; anything else is
                    # a conflict the executor would silently last-win
                    if not (n in ctx.persistables and n in declared):
                        ctx.error(
                            "write-conflicts",
                            f"{n!r} is written by op #{prev} and again by "
                            f"op #{i}; the second write silently wins "
                            "(declare __inplace__ on a persistable state "
                            "chain, or write distinct vars)",
                            block_idx=blk.idx, op_index=i, op_type=op.type,
                            var=n)
                else:
                    writers[n] = i


# ---------------------------------------------------------------------------
# 4. dtype-consistency: declared output dtypes vs the kernel's inference
# ---------------------------------------------------------------------------

_DYN = 83  # op_append.py's dynamic-dim placeholder (prime & recognizable)


@register_pass("dtype-consistency")
def _dtype_consistency(ctx):
    import jax  # deferred: the lint half of analysis must not need jax
    import numpy as np

    from ..ops.registry import _REGISTRY

    for blk in ctx.program.blocks:
        for i, op in enumerate(blk.ops):
            if op.type in _BLOCK_OPS or op.type.startswith("grad::"):
                continue  # lowered structurally / via jax.vjp, not a kernel
            if op.type == "init_param":
                continue  # startup-program op, interpreted host-side
            opdef = _REGISTRY.get(op.type)
            if opdef is None:
                ctx.error("dtype-consistency",
                          f"op type {op.type!r} is not in the kernel "
                          "registry; the executor cannot lower it",
                          block_idx=blk.idx, op_index=i, op_type=op.type)
                continue
            in_names = op_in_names(op)
            specs = []
            for n in in_names:
                var = ctx.resolve_var(blk, n) if n else None
                if var is None or var.shape is None:
                    specs = None  # unknown operand: inference inconclusive
                    break
                shape = tuple(_DYN if d in (-1, None) else d
                              for d in var.shape)
                specs.append(jax.ShapeDtypeStruct(shape, var.dtype))
            if specs is None:
                continue
            attrs = {k: v for k, v in op.attrs.items()
                     if not k.startswith("__")}
            if op.attrs.get("__rng__"):
                attrs["key"] = jax.random.key(0)
            try:
                out = jax.eval_shape(lambda *xs: opdef.fn(*xs, **attrs),
                                     *specs)
            except Exception as e:  # inconclusive, not provably wrong
                ctx.warn("dtype-consistency",
                         f"kernel shape/dtype inference failed "
                         f"({type(e).__name__}: {str(e)[:160]}); op left "
                         "unchecked", block_idx=blk.idx, op_index=i,
                         op_type=op.type)
                continue
            out_specs = list(out) if isinstance(out, (tuple, list)) else [out]
            out_names = op_out_names(op)
            if len([n for n in out_names if n]) > len(out_specs):
                ctx.error("dtype-consistency",
                          f"op declares {len(out_names)} outputs but its "
                          f"kernel yields {len(out_specs)}",
                          block_idx=blk.idx, op_index=i, op_type=op.type)
                continue
            for name, spec in zip(out_names, out_specs):
                if not name:
                    continue
                var = ctx.resolve_var(blk, name)
                if var is None:
                    continue  # def-before-use territory
                declared = np.dtype(var._meta["dtype"])
                inferred = np.dtype(spec.dtype)
                if declared != inferred:
                    ctx.error(
                        "dtype-consistency",
                        f"output {name!r} is declared {declared} but the "
                        f"{op.type!r} kernel produces {inferred} for these "
                        "operands",
                        block_idx=blk.idx, op_index=i, op_type=op.type,
                        var=name)


# ---------------------------------------------------------------------------
# 5. dead-code: ops/vars unreachable from fetches + persistable writes
# ---------------------------------------------------------------------------

def _writes_persistables(ctx, block_idx, seen=None):
    """Does the block (or any nested sub-block) write a persistable?"""
    seen = seen or set()
    if block_idx in seen or not (0 <= block_idx < len(ctx.program.blocks)):
        return False
    seen.add(block_idx)
    blk = ctx.program.blocks[block_idx]
    for op in blk.ops:
        if any(n in ctx.persistables for n in all_out_names(op) if n):
            return True
        if op.type in _BLOCK_OPS:
            for bkey, _ in _SUBBLOCK_SPEC.get(op.type, ()):
                bidx = op.attrs.get(bkey)
                if isinstance(bidx, int) and _writes_persistables(
                        ctx, bidx, seen):
                    return True
    return False


@register_pass("dead-code")
def _dead_code(ctx):
    program = ctx.program

    def live_walk(block_idx, roots, visiting):
        """Reverse-walk one block; emit a warning per dead op, recurse
        into live control-flow ops' sub-blocks."""
        blk = program.blocks[block_idx]
        live = set(roots)
        for i in range(len(blk.ops) - 1, -1, -1):
            op = blk.ops[i]
            outs = [n for n in all_out_names(op) if n]
            side_effecting = (
                not outs  # nothing to track: assume effects
                or any(n in ctx.persistables for n in outs)
            )
            if not side_effecting and op.type in _BLOCK_OPS:
                side_effecting = any(
                    _writes_persistables(ctx, op.attrs.get(bkey, -1))
                    for bkey, _ in _SUBBLOCK_SPEC.get(op.type, ()))
            if side_effecting or any(n in live for n in outs):
                live.update(n for n in all_in_names(op) if n)
                live.update(_attr_names(op))
                if op.type in _BLOCK_OPS:
                    for bkey, _ in _SUBBLOCK_SPEC.get(op.type, ()):
                        bidx = op.attrs.get(bkey)
                        if (isinstance(bidx, int)
                                and 0 < bidx < len(program.blocks)
                                and bidx not in visiting):
                            live_walk(bidx, _subblock_roots(op),
                                      visiting | {block_idx})
            else:
                first = outs[0] if outs else None
                ctx.warn(
                    "dead-code",
                    f"op result {outs} is unreachable from the fetch "
                    "targets and writes no persistable state; the op is "
                    "dead weight in the compiled block",
                    block_idx=blk.idx, op_index=i, op_type=op.type,
                    var=first)

    live_walk(0, set(ctx.fetch_names), frozenset())

    # dead vars: declared but referenced by nothing at all
    referenced = set(ctx.fetch_names) | set(ctx.feed_names) | ctx.constants
    for blk in program.blocks:
        for op in blk.ops:
            referenced.update(n for n in all_in_names(op) if n)
            referenced.update(n for n in all_out_names(op) if n)
            referenced.update(_attr_names(op))
    for blk in program.blocks:
        for name, var in blk.vars.items():
            if name in referenced:
                continue
            if getattr(var, "persistable", False) or var._meta.get("is_data"):
                continue  # loadable / feedable by name at any time
            ctx.warn("dead-code",
                     f"var {name!r} is declared in block {blk.idx} but "
                     "referenced by no op, feed, or fetch",
                     block_idx=blk.idx, var=name)


def _subblock_roots(op):
    roots = []
    for key in ("__body_outs__", "__carry_outs__", "__y_outs__",
                "__true_outs__", "__false_outs__"):
        roots.extend(n for n in op.attrs.get(key, ()) if n)
    if op.attrs.get("__cond_out__"):
        roots.append(op.attrs["__cond_out__"])
    return roots
