"""paddle_tpu.linalg — the 2.0 linear-algebra namespace.

Reference parity: the paddle.linalg namespace emerging in the 2.0 API
rework (python/paddle/tensor/linalg.py backs it in the snapshot).
"""
from .tensor.linalg import (  # noqa: F401
    bmm,
    cholesky,
    cross,
    det,
    dist,
    dot,
    eig,
    eigh,
    histogram,
    inverse,
    lstsq,
    matmul,
    matrix_power,
    matrix_rank,
    mv,
    norm,
    pinv,
    qr,
    slogdet,
    solve,
    svd,
    t,
    transpose,
    triangular_solve,
)
