"""Device memory/observability facade (paddle.device surface).

Reference parity: python/paddle/device/ + paddle.device.cuda memory APIs
(memory_allocated/max_memory_allocated/memory_reserved, synchronize,
device_count, Stream/Event no-ops) over the reference's allocator
telemetry (memory/allocation/allocator_facade.cc stats).

TPU-native: XLA owns the device arena — there is no framework allocator
to query, but the PJRT device exposes the arena's live/peak/limit
counters (``Device.memory_stats()``), which is exactly what the
reference's facade reports. On backends without stats (CPU; the
axon-tunneled TPU, whose PJRT proxy does not forward the counters) the
functions return 0 rather than raising, matching paddle's behavior on
hosts without the accelerator runtime.
"""
from __future__ import annotations

import jax

from .framework.place import get_device, set_device  # noqa: F401

__all__ = [
    "set_device", "get_device", "device_count", "get_device_name",
    "synchronize", "memory_allocated", "max_memory_allocated",
    "memory_reserved", "memory_stats", "empty_cache", "is_compiled_with_cuda",
]


def device_count() -> int:
    return len(jax.local_devices())


def _dev(device=None):
    devs = jax.local_devices()
    if device is None:
        return devs[0]
    if isinstance(device, int):
        return devs[device]
    if isinstance(device, str):
        # accept the formats paddle's own get_device emits: "tpu:0",
        # "cpu", "gpu:1"
        idx = int(device.rsplit(":", 1)[1]) if ":" in device else 0
        return devs[idx]
    return device


def get_device_name(device=None) -> str:
    d = _dev(device)
    return getattr(d, "device_kind", str(d))


def synchronize(device=None):
    """Block until previously dispatched work on the device finishes
    (paddle.device.cuda.synchronize parity; XLA dispatch is async)."""
    jax.block_until_ready(jax.device_put(0, _dev(device)))


def memory_stats(device=None) -> dict:
    """The PJRT arena counters (allocator_facade stats equivalent);
    empty dict when the backend publishes none (CPU)."""
    return _dev(device).memory_stats() or {}


def memory_allocated(device=None) -> int:
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    """Bytes the runtime currently holds from the device (the reference's
    allocator-reserved-pool semantics, memory/allocation/allocator_facade).

    PJRT publishes no reserved-pool counter, so the closest truthful
    figure is ``peak_bytes_in_use`` — the arena's high-water mark, a floor
    on what the runtime holds. Returns 0 when the backend publishes no
    counters at all. NOT ``bytes_limit``: that is total addressable HBM
    capacity, and reporting it here would make reserved look like the
    whole chip (use ``memory_stats()['bytes_limit']`` for capacity).
    """
    s = memory_stats(device)
    return int(s.get("peak_bytes_in_use", 0))


def empty_cache():
    """paddle.device.cuda.empty_cache parity. XLA's arena is not
    framework-managed; the real lever is dropping dead jax array
    references, so this triggers a host GC pass (which releases device
    buffers whose Python owners died)."""
    import gc

    gc.collect()


def is_compiled_with_cuda() -> bool:
    return False  # TPU build
