"""High-level training API (paddle.Model).

Reference parity: python/paddle/incubate/hapi/ — model.py (Model :637,
fit :1110, evaluate :1309, predict :1406, DynamicGraphAdapter :443),
callbacks.py, progressbar.
"""
from .model import Model  # noqa: F401
from .callbacks import Callback, EarlyStopping, ModelCheckpoint, ProgBarLogger  # noqa: F401
