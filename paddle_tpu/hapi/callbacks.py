"""Training callbacks (incubate/hapi/callbacks.py)."""
from __future__ import annotations

import numpy as np


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for cb in self.callbacks:
                getattr(cb, name)(*args, **kwargs)

        return call

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)


class ProgBarLogger(Callback):
    """Per-epoch stdout logging (simplified progress bar)."""

    def __init__(self, log_freq=10, verbose=1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = 0

    def on_train_batch_end(self, step, logs=None):
        self.steps += 1
        if self.verbose and self.steps % self.log_freq == 0:
            msg = " - ".join(
                f"{k}: {float(np.asarray(v)):.4f}"
                for k, v in (logs or {}).items()
                if np.ndim(v) == 0 or np.size(v) == 1
            )
            print(f"Epoch {self.epoch} step {self.steps}: {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            msg = " - ".join(
                f"{k}: {float(np.asarray(v)):.4f}"
                for k, v in (logs or {}).items()
                if np.ndim(v) == 0 or np.size(v) == 1
            )
            print(f"Epoch {epoch} done: {msg}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="min", patience=0, min_delta=0,
                 baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.wait = 0
        self.best = None
        self.stopped_epoch = 0
        self.mode = mode
        self.stop_training = False

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = float(np.asarray(logs[self.monitor]).reshape(-1)[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                self.model.stop_training = True
