"""paddle.Model — Keras-like train/eval/predict facade.

Reference parity: incubate/hapi/model.py (Model :637, fit :1110,
evaluate :1309, predict :1406). The DynamicGraphAdapter's per-batch
train_batch is replaced by a compiled train step
(framework/jit.py), optionally sharded over a mesh when one is active —
so Model.fit is TPU-efficient out of the box.
"""
from __future__ import annotations

import numpy as np

from ..framework import jit as fjit
from ..framework.autograd import no_grad
from ..framework.tensor import Tensor
from ..io import DataLoader
from .callbacks import Callback, CallbackList, ProgBarLogger

__all__ = ["Model"]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self.stop_training = False
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self._eval_fn = None

    # -- configuration ------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else (
            [metrics] if metrics is not None else []
        )
        self._amp = amp_configs
        self._train_step = None
        return self

    # -- core steps ---------------------------------------------------------
    def _build_train_step(self):
        loss_obj = self._loss

        use_amp = bool(self._amp)

        def loss_fn(network, *batch):
            # convention: last element is the label
            *xs, y = batch
            if use_amp:
                from .. import amp as amp_mod

                with amp_mod.auto_cast():
                    out = network(*xs)
                out = out.astype("float32")
            else:
                out = network(*xs)
            loss = loss_obj(out, y)
            if isinstance(loss, (list, tuple)):
                loss = loss[0]
            return loss.mean() if loss.ndim > 0 else loss

        from ..parallel.mesh import get_mesh

        # fleet.distributed_optimizer carries a DistributedStrategy; the
        # step builder consumes it (recompute/gradient_merge/ZeRO-1/localsgd)
        strategy = getattr(self._optimizer, "user_defined_strategy", None)
        opt = getattr(self._optimizer, "inner_opt", self._optimizer)

        mesh = get_mesh()
        if mesh is not None:
            from ..parallel import sharded_train_step

            return sharded_train_step(
                self.network, opt, loss_fn, mesh, strategy=strategy
            )
        if strategy is not None:
            from ..parallel.train import consume_strategy

            o = consume_strategy(strategy)
            if o.get("localsgd") or o.get("zero1"):
                raise RuntimeError(
                    "strategy.localsgd/sharding need a device mesh: wrap "
                    "training in parallel.mesh_scope(create_mesh(dp=...))"
                )
            if o.get("amp"):
                from ..parallel.train import _amp_wrap

                loss_fn = _amp_wrap(loss_fn, strategy)
            return fjit.train_step(
                self.network, opt, loss_fn,
                recompute=o["recompute"],
                grad_accum_steps=o["grad_accum_steps"],
                grad_accum_avg=o["grad_accum_avg"],
            )
        return fjit.train_step(self.network, opt, loss_fn)

    def train_batch(self, inputs, labels=None):
        if self._train_step is None:
            self._train_step = self._build_train_step()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is not None else []
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        metrics = self._train_step(*inputs, *labels)
        return {"loss": float(np.asarray(metrics["loss"]))}

    def eval_batch(self, inputs, labels=None):
        self._sync_from_step()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self.network.eval()
        try:
            t_in = [_to_tensor(x) for x in inputs]
            out = self.network(*t_in)
        finally:
            self.network.train()
        logs = {}
        if labels is not None and self._loss is not None:
            y = _to_tensor(labels if not isinstance(labels, (list, tuple)) else labels[0])
            loss = self._loss(out, y)
            if isinstance(loss, (list, tuple)):
                loss = loss[0]
            logs["loss"] = float(np.asarray(loss.mean().numpy()))
        for m in self._metrics:
            y = labels if not isinstance(labels, (list, tuple)) else labels[0]
            res = m.compute(out, _to_tensor(y))
            m.update(res)
        return logs, out

    def predict_batch(self, inputs):
        self._sync_from_step()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self.network.eval()
        try:
            out = self.network(*[_to_tensor(x) for x in inputs])
        finally:
            self.network.train()
        return out

    def _sync_from_step(self):
        if self._train_step is not None:
            self._train_step.sync()

    # -- high-level loops ---------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=1,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None):
        loader = _as_loader(train_data, batch_size, shuffle, drop_last,
                            num_workers)
        eval_loader = (
            _as_loader(eval_data, batch_size, False, False, num_workers)
            if eval_data is not None else None
        )
        cbks = CallbackList(
            (callbacks or []) + ([ProgBarLogger(log_freq, verbose)] if verbose else [])
        )
        cbks.set_model(self)
        cbks.set_params({"epochs": epochs, "verbose": verbose})
        self.stop_training = False

        cbks.on_train_begin()
        logs = {}

        # auto-checkpoint (fluid/incubate/checkpoint/auto_checkpoint.py):
        # when the PADDLE_EDL_AUTO_CHECKPOINT env is configured, fit
        # resumes from the newest snapshot and snapshots periodically;
        # train_epoch_range degrades to plain range() otherwise
        from ..incubate import auto_checkpoint as acp

        if acp.AutoCheckpointChecker().valid():
            self._sync_from_step()
            # namespace per model instance: a fixed name would let a second
            # Model.fit in the same process hijack the first one's snapshots;
            # the claimed name is deterministic so restarted programs resume.
            # A cached name goes stale when reset_registry() ran (elastic
            # restart) — re-claim so surviving and rebuilt models cannot
            # collide on the restarted counter.
            if (getattr(self, "_acp_epoch", None) != acp.registry_epoch()
                    or not hasattr(self, "_acp_name")):
                self._acp_name = acp.claim_name(type(self.network).__name__)
                self._acp_epoch = acp.registry_epoch()
            acp.register(self.network, self._optimizer,
                         name=self._acp_name,
                         sync_fn=self._sync_from_step)
            # the restore (inside train_epoch_range) rewrites the eager
            # state; drop any compiled step so it rebuilds from it
            self._train_step = None
            epoch_iter = acp.train_epoch_range(epochs)
        else:
            epoch_iter = iter(range(epochs))

        # step-level utilization telemetry: every fit rides the
        # TrainingMonitor (periodic line behind FLAGS_monitor_interval;
        # close() flushes the partial window so short fits still report).
        # verbose=0 keeps the historical silent-stdout contract —
        # aggregation still runs, only the line is suppressed.
        from ..monitor import TrainingMonitor

        # chaos harness hook: FLAGS_fault_injection directives fire at
        # the train-step boundary (kill -9 / delay / hard-exit) so every
        # recovery path is exercised by a real process death. Idle cost
        # is one flag read per batch.
        from ..distributed import chaos

        mon = TrainingMonitor("fit", interval=None if verbose else 0)
        gstep = 0
        try:
            for epoch in epoch_iter:
                cbks.on_epoch_begin(epoch)
                for step, batch in enumerate(loader):
                    chaos.inject("step", step=gstep)
                    gstep += 1
                    cbks.on_train_batch_begin(step)
                    xs, ys = _split_batch(batch)
                    with mon.step(examples=_batch_examples(xs)):
                        logs = self.train_batch(xs, ys)
                    cbks.on_train_batch_end(step, logs)
                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    eval_logs = self.evaluate(
                        eval_loader, batch_size=batch_size, verbose=0,
                        _prepared=True,
                    )
                    logs.update(
                        {f"eval_{k}": v for k, v in eval_logs.items()})
                    cbks.on_eval_end(eval_logs)
                cbks.on_epoch_end(epoch, logs)
                if save_dir and (epoch + 1) % save_freq == 0:
                    self.save(f"{save_dir}/{epoch}")
                if self.stop_training:
                    break
        finally:
            mon.close()
            if acp.AutoCheckpointChecker().valid():
                # even on an abnormal exit, in-flight async snapshots
                # must become durable (or fail loudly) before fit returns
                # — a silently dropped snapshot would widen the redo
                # window of the NEXT crash. Writer errors re-raise only
                # when the loop itself succeeded (never mask the
                # training exception).
                import sys as _sys

                acp.wait_pending(
                    raise_errors=_sys.exc_info()[0] is None)
        cbks.on_train_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=1,
                 num_workers=0, callbacks=None, _prepared=False):
        loader = (
            eval_data if _prepared
            else _as_loader(eval_data, batch_size, False, False, num_workers)
        )
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            xs, ys = _split_batch(batch)
            logs, _ = self.eval_batch(xs, ys)
            if "loss" in logs:
                losses.append(logs["loss"])
        out = {}
        if losses:
            out["loss"] = float(np.mean(losses))
        for m in self._metrics:
            name = m.name()
            val = m.accumulate()
            if isinstance(name, list):
                out.update(dict(zip(name, val)))
            else:
                out[name] = val
        return out

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                callbacks=None):
        loader = _as_loader(test_data, batch_size, False, False, num_workers)
        outs = []
        for batch in loader:
            # labeled datasets (x..., y): the trailing label is dropped,
            # matching hapi predict over a train dataset
            xs, _ = _split_batch(batch)
            out = self.predict_batch(xs)
            outs.append(
                out.numpy() if isinstance(out, Tensor) else out
            )
        if stack_outputs:
            return np.concatenate(outs, axis=0)
        return outs

    # -- persistence / introspection ----------------------------------------
    def save(self, path, training=True):
        from ..framework import serialization

        self._sync_from_step()
        serialization.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            serialization.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework import serialization

        state = serialization.load(path + ".pdparams", return_numpy=True)
        self.network.set_state_dict(state)
        self._train_step = None
        if not reset_optimizer and self._optimizer is not None:
            try:
                opt_state = serialization.load(path + ".pdopt", return_numpy=True)
                self._optimizer.set_state_dict(opt_state)
            except FileNotFoundError:
                pass

    def serve(self, input_spec, host="127.0.0.1", port=0, model_dir=None,
              warmup=True, **serving_kwargs):
        """Export the trained network and serve it online.

        Captures the network as a static inference program (``jit.save``
        over ``input_spec``), loads it into an inference ``Predictor``,
        and starts an :class:`~paddle_tpu.serving.InferenceServer` on
        ``host:port`` (``port=0``: ephemeral) — dynamic batching, the
        replica pool, and warmed-bucket readiness included. Extra
        keyword args (``replicas``, ``buckets``, ``queue_capacity``,
        ``batch_timeout_ms``) pass through to the server. Returns the
        started server; call ``.stop(drain=True)`` to shut down.
        """
        import tempfile

        from .. import jit_api
        from ..inference import Config, create_predictor
        from ..serving import InferenceServer

        self._sync_from_step()
        specs = [
            s if isinstance(s, jit_api.InputSpec) else jit_api.InputSpec(s)
            for s in input_spec
        ]
        dirname = model_dir or tempfile.mkdtemp(prefix="ptpu_serve_")
        jit_api.save(self.network, dirname, input_spec=specs)
        predictor = create_predictor(Config(dirname))
        server = InferenceServer(predictor, port=port, host=host,
                                 **serving_kwargs)
        return server.start(warmup=warmup)

    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        total = sum(int(np.prod(p.shape)) for p in self.network.parameters())
        trainable = sum(
            int(np.prod(p.shape))
            for p in self.network.parameters()
            if getattr(p, "trainable", True)
        )
        s = {
            "total_params": total,
            "trainable_params": trainable,
        }
        print(f"Total params: {total:,} (trainable {trainable:,})")
        return s


def _to_tensor(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(np.asarray(x))


def _batch_examples(xs):
    """Leading-dim size of the first input (None when unknowable).
    Reads ``.shape`` metadata only — never np.asarray, which would force
    a device sync per batch just to label the monitor line."""
    x = xs[0] if isinstance(xs, (list, tuple)) and xs else xs
    shape = getattr(x, "shape", None)
    if shape is None and isinstance(x, (list, tuple)):
        shape = (len(x),)
    try:
        return int(shape[0]) if shape else None
    except Exception:
        return None


def _split_batch(batch, labeled=True):
    if isinstance(batch, (list, tuple)) and len(batch) >= 2 and labeled:
        return list(batch[:-1]), batch[-1]
    if isinstance(batch, (list, tuple)):
        return list(batch), None
    return [batch], None


def _as_loader(data, batch_size, shuffle, drop_last, num_workers):
    if isinstance(data, DataLoader):
        return data
    return DataLoader(
        data, batch_size=batch_size, shuffle=shuffle, drop_last=drop_last,
        num_workers=num_workers,
    )


def _layer_cost(layer, args, kwargs):
    """FLOPs + bytes for one layer call via XLA's HLO cost analysis
    (no backend compile — client-side analysis of the lowered module;
    the None/partial-analysis guard lives in monitor.cost_model)."""
    import jax

    from ..monitor import cost_model

    state = fjit.capture_state(layer)

    def pure(state, args):
        out, _ = fjit.functional_call(layer, state, *args, **kwargs)
        return out

    try:
        lowered = jax.jit(pure).lower(state, args)
    except Exception:
        return None  # non-traceable layer (dynamic control flow, ...)
    return cost_model.flops_and_bytes(lowered)


def summary(net, input_size=None, dtypes=None, cost=False):
    """paddle.summary (hapi/model_summary.py): layer table + param counts.

    ``cost=True`` (beyond-reference, replacing contrib/model_stat.py:1's
    hand-maintained FLOPs table): runs one shape-capturing forward over
    ``input_size`` and reports per-leaf-layer FLOPs and HBM bytes from
    XLA's cost analysis of each layer's lowered HLO — the numbers the
    compiler itself schedules against, not a formula that drifts from
    the implementation. Requires ``input_size``.
    """
    import numpy as np_

    captured = {}  # id(layer) -> (args, kwargs)
    cost_rows = {}
    if cost:
        if input_size is None:
            raise ValueError("summary(cost=True) needs input_size")
        hooks = []
        leaves = [(n, l) for n, l in net.named_sublayers()
                  if not list(l.children())]
        if not leaves:  # the net itself is a single leaf layer
            leaves = [(type(net).__name__, net)]

        def make_hook(lid):
            def pre_hook(layer, inputs):
                if lid not in captured:
                    captured[lid] = tuple(
                        t._array if isinstance(t, Tensor) else t
                        for t in inputs
                    )
                return None
            return pre_hook

        for _, l in leaves:
            hooks.append(l.register_forward_pre_hook(make_hook(id(l))))
        sizes = (input_size if isinstance(input_size, (list, tuple))
                 and isinstance(input_size[0], (list, tuple))
                 else [input_size])
        dts = dtypes or ["float32"] * len(sizes)
        if isinstance(dts, str):
            dts = [dts] * len(sizes)
        xs = [Tensor(np_.zeros(s, dtype=d)) for s, d in zip(sizes, dts)]
        uncosted = []
        was_training = net.training
        net.eval()
        # per-layer attribution needs the per-layer graph: cross-layer
        # fusions (the conv+bn+relu triple skips conv.forward entirely)
        # would leave their layers uncaptured and the census short. The
        # fusion flag is scheduling-only by contract (identical math),
        # so the unfused census is THE census.
        from ..flags import get_flags, set_flags
        prev_fuse = get_flags(["use_fused_conv_bn"])
        set_flags({"use_fused_conv_bn": False})
        try:
            with no_grad():
                net(*xs)
            # lower per-layer costs INSIDE the eval window, so the cost
            # graphs match the captured eval-mode activations (BN uses
            # running stats, dropout is identity)
            for name, l in leaves:
                if id(l) not in captured:
                    continue
                c = _layer_cost(l, captured[id(l)], {})
                if c is not None:
                    cost_rows[name] = c
                else:
                    uncosted.append(name)
        finally:
            set_flags(prev_fuse)
            if was_training:
                net.train()
            for h in hooks:
                h.remove()
        never_ran = [n for n, l in leaves if id(l) not in captured]
        uncosted.extend(never_ran)

    rows = []
    total, trainable = 0, 0
    for name, layer in [("", net)] + list(net.named_sublayers()):
        own = sum(
            int(np_.prod(p.shape)) for p in layer._parameters.values()
            if p is not None
        )
        if own or not name or name in cost_rows:
            cls = type(layer).__name__
            rows.append((name or cls, cls, own))
    for _, p in net.named_parameters():
        n = int(np_.prod(p.shape))
        total += n
        if getattr(p, "trainable", True):
            trainable += n
    hdr = f"{'Layer':40s} {'Type':24s} {'Params':>12s}"
    if cost:
        hdr += f" {'FLOPs':>14s} {'Bytes':>14s}"
    lines = [hdr]
    for n, c, p in rows:
        line = f"{n[:40]:40s} {c[:24]:24s} {p:12,d}"
        if cost and n in cost_rows:
            fl, by = cost_rows[n]
            line += f" {fl:14,.0f} {by:14,.0f}"
        lines.append(line)
    lines.append("-" * (78 + (30 if cost else 0)))
    lines.append(f"Total params: {total:,d}")
    lines.append(f"Trainable params: {trainable:,d}")
    lines.append(f"Non-trainable params: {total - trainable:,d}")
    out = {"total_params": total, "trainable_params": trainable}
    if cost:
        total_flops = sum(f for f, _ in cost_rows.values())
        total_bytes = sum(b for _, b in cost_rows.values())
        lines.append(f"Total FLOPs (fwd, per-layer sum): {total_flops:,.0f}")
        lines.append(f"Total bytes accessed: {total_bytes:,.0f}")
        out["layer_costs"] = cost_rows
        out["total_flops"] = total_flops
        out["total_bytes"] = total_bytes
        out["uncosted_layers"] = uncosted
        if uncosted:
            # never let skipped layers masquerade as fusion savings
            lines.append(
                f"NOT costed ({len(uncosted)} layers — lowering failed, "
                f"totals underreport): {', '.join(uncosted[:8])}"
                + ("…" if len(uncosted) > 8 else ""))
    text = "\n".join(lines)
    print(text)
    return out
