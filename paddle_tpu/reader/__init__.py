"""Reader decorator library (paddle.reader).

Reference parity: python/paddle/reader/decorator.py:36 (cache), :60
(map_readers), :102 (shuffle), :151 (chain), :216 (compose), :276
(buffered), :319 (firstn), :364 (xmap_readers), :457
(multiprocess_reader). A "reader" is a zero-arg callable returning an
iterator of samples; decorators wrap readers into new readers — the book-
style data-pipeline idiom that predates DataLoader.

TPU-native notes: these run on the host and feed the DataLoader /
Dataset paths; buffered/xmap use threads + queues (the host side is IO
bound, the GIL is released in file/np ops), and xmap's ordered mode uses
a condition variable instead of the reference's spin-wait
(decorator.py:414 ``while order != out_order[0]: pass`` burns a core).
"""
from __future__ import annotations

import itertools
import random as _random
from queue import Queue
from threading import Condition, Thread

__all__ = [
    "cache", "map_readers", "shuffle", "chain", "compose", "buffered",
    "firstn", "xmap_readers", "multiprocess_reader", "ComposeNotAligned",
]


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    """Materialize ``reader()`` once; replay from memory afterwards."""
    all_data = tuple(reader())

    def __impl__():
        return iter(all_data)

    return __impl__


def map_readers(func, *readers):
    """Reader yielding ``func(*samples)`` over the zipped input readers."""

    def reader():
        rs = [r() for r in readers]
        for e in map(func, *rs):
            yield e

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of ``buf_size`` samples."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            _random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers back to back (format unchanged)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, **kwargs):
    """Zip readers side by side, flattening tuple outputs.

    ``check_alignment=True`` (default) raises ComposeNotAligned when the
    readers have different lengths; False silently truncates to the
    shortest.
    """
    check_alignment = kwargs.pop("check_alignment", True)
    if kwargs:
        raise TypeError(f"unexpected kwargs {sorted(kwargs)}")

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(map(make_tuple, outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned (different "
                        "lengths); pass check_alignment=False to truncate")
                yield sum(map(make_tuple, outputs), ())

    return reader


class _End:
    pass


class _Raise:
    """Error marker forwarded from a worker thread to the consumer —
    a reader that dies must raise, never silently truncate the stream."""

    def __init__(self, exc):
        self.exc = exc


def buffered(reader, size):
    """Prefetch up to ``size`` samples on a background thread."""

    end = _End()

    def read_worker(it, q):
        try:
            for d in it:
                q.put(d)
        except Exception as e:
            q.put(_Raise(e))
        finally:
            q.put(end)

    def data_reader():
        q = Queue(maxsize=size)
        t = Thread(target=read_worker, args=(reader(), q), daemon=True)
        t.start()
        e = q.get()
        while e is not end:
            if isinstance(e, _Raise):
                raise RuntimeError("buffered reader source failed") from e.exc
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    """Limit the reader to its first ``n`` samples."""

    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Map samples with ``process_num`` worker threads.

    ``order=True`` preserves the source order (condition-variable
    hand-off — no spin-wait).
    """
    end = _End()

    def data_reader():
        in_q = Queue(buffer_size)
        out_q = Queue(buffer_size)

        def read_worker():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except Exception as e:
                out_q.put(_Raise(e))
            finally:
                in_q.put(end)

        cond = Condition()
        state = {"next": 0}

        def handle_worker():
            while True:
                item = in_q.get()
                if item is end:
                    in_q.put(end)  # wake the other workers
                    out_q.put(end)
                    return
                idx, sample = item
                try:
                    r = mapper(sample)
                except Exception as e:
                    # a dying worker must still release ordered peers
                    # waiting for this index and deliver its end marker
                    if order:
                        with cond:
                            state["next"] = max(state["next"], idx + 1)
                            cond.notify_all()
                    out_q.put(_Raise(e))
                    out_q.put(end)
                    return
                if order:
                    with cond:
                        while state["next"] != idx:
                            cond.wait()
                        out_q.put(r)
                        state["next"] += 1
                        cond.notify_all()
                else:
                    out_q.put(r)

        Thread(target=read_worker, daemon=True).start()
        for _ in range(process_num):
            Thread(target=handle_worker, daemon=True).start()
        finished = 0
        while finished < process_num:
            e = out_q.get()
            if e is end:
                finished += 1
            elif isinstance(e, _Raise):
                raise RuntimeError("xmap_readers worker failed") from e.exc
            else:
                yield e

    return data_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Run several readers in worker PROCESSES, merging their outputs.

    decorator.py:457 — the reference forks one process per reader and
    multiplexes over a multiprocessing queue/pipe; samples interleave in
    arrival order. Requires the readers (and their samples) to be
    picklable.
    """
    import multiprocessing as mp

    if len(readers) < 1:
        raise ValueError("readers number must be greater than 0")

    def queue_reader():
        ctx = mp.get_context("fork")
        q = ctx.Queue(queue_size)

        def worker(r):
            try:
                for s in r():
                    q.put(s)
            except Exception as e:  # propagate loudly, never hang
                q.put(("__mp_reader_error__", f"{type(e).__name__}: {e}"))
            finally:
                q.put(None)

        procs = [ctx.Process(target=worker, args=(r,), daemon=True)
                 for r in readers]
        for p in procs:
            p.start()
        finished = 0
        while finished < len(readers):
            sample = q.get()
            if sample is None:
                finished += 1
            elif (isinstance(sample, tuple) and len(sample) == 2
                  and sample[0] == "__mp_reader_error__"):
                raise RuntimeError(f"multiprocess_reader worker: {sample[1]}")
            else:
                yield sample
        for p in procs:
            p.join(timeout=5.0)

    return queue_reader
