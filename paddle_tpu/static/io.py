"""Static-graph model serialization.

Reference parity: python/paddle/fluid/io.py (save_persistables,
save_inference_model :? , load_inference_model, save/load state) over
save_op/load_op/save_combine_op (operators/save_combine_op.cc).

Format: `<path>/__model__` holds the serialized Program (JSON — our
ProgramDesc form); `<path>/__params__` holds all persistable variables in
one combined file (save_combine semantics) via framework.serialization.
"""
from __future__ import annotations

import os

import numpy as np

from ..framework import serialization
from .executor import global_scope
from .program import Program, default_main_program

__all__ = [
    "save", "load", "save_persistables", "load_persistables",
    "save_inference_model", "load_inference_model",
]

_MODEL_FILENAME = "__model__"
_PARAMS_FILENAME = "__params__"


def _persistable_dict(program, scope=None):
    scope = scope or global_scope()
    out = {}
    for var in program.list_vars():
        if var.persistable and scope.has(var.name):
            out[var.name] = np.asarray(scope.get(var.name))
    # eager tensors captured into the program as constants (op_append.py)
    # are authoritative over any same-named value a previously-loaded
    # program left in the global scope
    for cname, cval in getattr(program, "_constants", {}).items():
        out[cname] = np.asarray(cval)
    return out


def save(program, model_path, protocol=4):
    """paddle.static.save: program params+buffers -> {path}.pdparams,
    program -> {path}.pdmodel."""
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    serialization.save(_persistable_dict(program), model_path + ".pdparams")
    with open(model_path + ".pdmodel", "wb") as f:
        f.write(program.serialize_to_string())


def load(program, model_path, executor=None, var_list=None):
    """paddle.static.load: restore persistables into the scope."""
    state = serialization.load(model_path + ".pdparams", return_numpy=True)
    scope = global_scope()
    names = (
        [v.name for v in var_list]
        if var_list is not None
        else [v.name for v in program.list_vars() if v.persistable]
    )
    for name in names:
        if name in state:
            scope.set(name, state[name])
    return program


def save_persistables(executor, dirname, main_program=None, filename=None):
    """fluid.io.save_persistables (save_combine semantics: one file)."""
    main_program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    serialization.save(
        _persistable_dict(main_program),
        os.path.join(dirname, filename or _PARAMS_FILENAME),
    )


def load_persistables(executor, dirname, main_program=None, filename=None):
    main_program = main_program or default_main_program()
    state = serialization.load(
        os.path.join(dirname, filename or _PARAMS_FILENAME),
        return_numpy=True,
    )
    scope = global_scope()
    for name, arr in state.items():
        scope.set(name, arr)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, **kwargs):
    """fluid.io.save_inference_model: prune the program to the inference
    subgraph reachable from feeds->fetches and save program+params.

    The reference prunes via ProgramDesc::Prune; here we keep ops whose
    outputs are (transitively) needed for target_vars, drop backward ops
    (op_role), and record the feed/fetch lists in the saved model.
    """
    main_program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    target_names = [
        v.name if hasattr(v, "name") else str(v) for v in target_vars
    ]

    pruned = _prune_for_inference(main_program, feeded_var_names, target_names)
    import json

    model = {
        "program": pruned.to_dict(),
        "feed_names": list(feeded_var_names),
        "fetch_names": target_names,
    }
    with open(os.path.join(dirname, model_filename or _MODEL_FILENAME), "w") as f:
        json.dump(model, f)
    serialization.save(
        _persistable_dict(pruned),
        os.path.join(dirname, params_filename or _PARAMS_FILENAME),
    )
    return target_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    """Returns (program, feed_names, fetch_names), params loaded into the
    global scope."""
    import json

    with open(os.path.join(dirname, model_filename or _MODEL_FILENAME)) as f:
        model = json.load(f)
    program = Program.from_dict(model["program"])
    state = serialization.load(
        os.path.join(dirname, params_filename or _PARAMS_FILENAME),
        return_numpy=True,
    )
    scope = global_scope()
    for name, arr in state.items():
        scope.set(name, arr)
    return program, model["feed_names"], model["fetch_names"]


def _prune_for_inference(program, feed_names, target_names):
    """Keep the forward subgraph producing target_names from feed_names."""
    block = program.global_block()
    kept_idx = []
    needed = set(target_names)
    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        if set(op.output_names()) & needed:
            kept_idx.append(i)
            needed |= set(op.input_names())
    kept_idx.reverse()

    pruned = Program.from_dict(program.to_dict())
    # captured eager constants don't survive to_dict; carry them over
    pruned._constants = dict(getattr(program, "_constants", {}))
    pblock = pruned.global_block()
    pblock.ops = [pblock.ops[i] for i in kept_idx]
    # drop vars not referenced anymore (keep persistables used by kept ops)
    used = set()
    for op in pblock.ops:
        used |= set(op.input_names()) | set(op.output_names())
    pblock.vars = {
        n: v for n, v in pblock.vars.items() if n in used
    }
    return pruned
