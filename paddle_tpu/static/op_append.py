"""Static-graph op appending.

Reference parity: fluid/layer_helper.py append_op + framework.py
Block.append_op. The mode-aware eager wrappers (paddle_tpu.ops._run) call
append_static_op when static mode is active, so the entire paddle_tpu.*
tensor API doubles as the static-graph layer API (the reference needed a
separate fluid/layers/ for this; the 2.0 unified API is what we mirror).

Output shapes/dtypes come from jax.eval_shape over the registered kernel —
there are no hand-written InferShape rules to drift out of sync.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.registry import kernel
from .program import Variable, default_main_program

# Dim placeholder for -1 (batch) dims during abstract eval; prime & unusual
# so we can recognize it in outputs and restore -1.
_DYN = 83

_GLOBAL_CONST_ID = [0]

RNG_OPS = {
    "dropout", "uniform_random", "gaussian_random", "randint", "randperm",
    "bernoulli", "multinomial", "truncated_gaussian_random",
}


def _spec_of(t):
    if isinstance(t, Variable):
        shape = [_DYN if d in (-1, None) else d for d in (t.shape or [])]
        return jax.ShapeDtypeStruct(tuple(shape), t.dtype)
    return jax.ShapeDtypeStruct(tuple(t._array.shape), t._array.dtype)


def capture_constant(t, block=None):
    """Capture an eager Tensor as a persistable constant Variable.

    Globally unique across programs: two captured programs must never share
    a constant name in the (shared) global scope.
    """
    prog = default_main_program()
    block = block or prog.current_block()
    _GLOBAL_CONST_ID[0] += 1
    cname = prog._unique_name(f"const{_GLOBAL_CONST_ID[0]}")
    cvar = block.create_var(name=cname, shape=list(t._array.shape),
                            dtype=str(t._array.dtype), persistable=True)
    if not hasattr(prog, "_constants"):
        prog._constants = {}
    prog._constants[cname] = np.asarray(t._array)
    return cvar


def append_static_op(op_type, tensors, attrs, alias_outputs=None):
    """Append an OpDesc to the current block; returns output Variable(s)."""
    from ..ops.registry import EAGER_ONLY_OPS

    if op_type in EAGER_ONLY_OPS:
        # build-time guardrail: the whole block compiles as one XLA
        # module (executor.py), so a data-dependent-shape op anywhere in
        # the program would make it unrunnable — reject with a clear
        # message now instead of an opaque trace error at exe.run
        from ..errors import UnimplementedError

        raise UnimplementedError(
            f"operator {op_type!r} has a data-dependent output shape and "
            "cannot appear in a static program (the block compiles to one "
            "XLA module with static shapes). Run it eagerly, or use the "
            "static-friendly alternative its docstring names "
            "(mask/pad/static-length forms)."
        )
    block = default_main_program().current_block()
    prog = default_main_program()

    in_names = []
    for t in tensors:
        if isinstance(t, Variable):
            in_names.append(t.name)
        else:
            in_names.append(capture_constant(t, block).name)

    run_attrs = dict(attrs)
    is_rng = op_type in RNG_OPS or "key" in run_attrs
    if is_rng:
        run_attrs.pop("key", None)

    # abstract eval for output specs
    fn = kernel(op_type)
    specs = [_spec_of(t) for t in tensors]

    def absfn(*xs):
        kw = dict(run_attrs)
        if is_rng:
            kw["key"] = jax.random.key(0)
        return fn(*xs, **kw)

    try:
        out_shape = jax.eval_shape(absfn, *specs)
    except Exception as e:
        # PADDLE_ENFORCE parity: shape-inference failures carry the op
        # context (InferShape errors in the reference name the operator,
        # platform/enforce.h); build-time is the earliest possible report
        from ..errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"shape inference failed for operator {op_type!r} with input "
            f"shapes {[tuple(s.shape) for s in specs]}: {e}",
            op_context={
                "op_type": op_type,
                "inputs": in_names,
                "outputs": [],
            },
        ) from e
    multi = isinstance(out_shape, (tuple, list))
    out_specs = list(out_shape) if multi else [out_shape]

    any_dynamic = any(
        isinstance(t, Variable) and t.shape and any(d in (-1, None) for d in t.shape)
        for t in tensors
    )

    out_vars = []
    out_names = []
    for i, sp in enumerate(out_specs):
        shape = [(-1 if (any_dynamic and d == _DYN) else d) for d in sp.shape]
        if alias_outputs and i in alias_outputs:
            name = alias_outputs[i]
            var = block.var(name)
        else:
            name = prog._unique_name(op_type)
            var = block.create_var(name=name, shape=shape, dtype=str(sp.dtype))
            var.stop_gradient = all(
                (not isinstance(t, Variable)) or t.stop_gradient for t in tensors
            ) or not jnp.issubdtype(sp.dtype, np.floating)
        out_names.append(name)
        out_vars.append(var)

    desc_attrs = dict(run_attrs)
    if alias_outputs:
        # declared in-place aliasing (batch_norm's running stats): the
        # op writes vars it also reads — the verifier's write-conflicts
        # pass accepts exactly the declared set and flags the rest
        desc_attrs["__inplace__"] = sorted(
            n for n in alias_outputs.values())
    if is_rng:
        desc_attrs["__rng__"] = True
        # stable per-op id assigned at build time: the grad op copies the
        # forward attrs, so its vjp replay folds the SAME id and reproduces
        # the forward's dropout mask (key = fold_in(step_key, id))
        counter = getattr(prog, "_rng_counter", 0)
        desc_attrs["__rng_id__"] = counter
        prog._rng_counter = counter + 1
    block.append_op(op_type, {"X": in_names}, {"Out": out_names}, desc_attrs)
    return tuple(out_vars) if multi else out_vars[0]
