"""paddle_tpu.static — static graph mode (reference: python/paddle/static/ +
python/paddle/fluid/ Program/Executor surface)."""
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from .io import (  # noqa: F401
    load,
    load_inference_model,
    load_persistables,
    save,
    save_inference_model,
    save_persistables,
)
from .backward import append_backward, gradients  # noqa: F401
from .control_flow import case, cond, scan, switch_case, while_loop  # noqa: F401
from ..jit_api import InputSpec  # noqa: F401
from .executor import Executor, Scope, global_scope  # noqa: F401
from .program import (  # noqa: F401
    Block,
    OpDesc,
    Program,
    VarDesc,
    Variable,
    data,
    default_main_program,
    default_startup_program,
    disable_static,
    enable_static,
    in_dynamic_mode,
    in_static_mode,
    program_guard,
    reset_default_programs,
)

# paddle.static.ExponentialMovingAverage (fluid/optimizer.py:3411) — the
# dygraph-state implementation works for static params too once pulled out
# of the scope; exported here for 2.x namespace parity.
from ..optimizer.wrappers import ExponentialMovingAverage  # noqa: F401
