"""Static-graph control flow: while_loop / cond / scan (+ case dispatch).

Reference parity: paddle/fluid/operators/controlflow/while_op.cc,
conditional_block_op.cc and python/paddle/fluid/layers/control_flow.py
(while_loop, cond, case, switch_case). Ops consume nested BlockDescs via
block-index attributes, exactly like the reference's BLOCK attr
(framework/framework.proto:34).

TPU-native lowering (static/executor.py):
- ``while_loop`` -> ``lax.while_loop``: dynamic trip count, NOT
  reverse-differentiable (XLA cannot backprop an unbounded loop). Use for
  inference-style iteration (decoding, convergence loops).
- ``cond`` -> ``lax.cond``: both branches compiled, predicate selects at
  run time; fully differentiable.
- ``scan`` -> ``lax.scan``: the differentiable bounded loop — the TPU
  answer to the reference's trainable RNN loops (recurrent_op /
  StaticRNN): time-major sequences with a static length, reverse-mode
  autodiff supported by construction.

Sub-block construction: the user fn runs under ``block_guard`` on fresh
placeholder Variables; every op it emits lands in the sub-block. Names the
sub-block reads but does not define ("captures", e.g. parameters) become
explicit op inputs so append_backward can route gradients to them.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .program import Variable, block_guard, default_main_program

__all__ = ["while_loop", "cond", "scan", "case", "switch_case"]

# attr keys holding sub-block indices (executor + serialization walk these)
BLOCK_ATTR_KEYS = (
    "__cond_block__", "__body_block__", "__true_block__", "__false_block__",
)


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _as_variables(vars_):
    """Coerce eager Tensors (e.g. ops.zeros run eagerly) to captured
    constant Variables so loop inputs are always program vars."""
    from .op_append import capture_constant

    out = []
    for v in _as_list(vars_):
        out.append(v if isinstance(v, Variable) else capture_constant(v))
    return out


def _placeholders(block, ref_vars, shapes=None, prefix="loopvar"):
    """Formal-argument Variables inside ``block`` mirroring ``ref_vars``."""
    prog = block.program
    out = []
    for i, v in enumerate(ref_vars):
        shape = shapes[i] if shapes is not None else v.shape
        ph = block.create_var(
            name=prog._unique_name(prefix), shape=shape, dtype=str(v.dtype)
        )
        ph.stop_gradient = v.stop_gradient
        out.append(ph)
    return out


def _trace_subblock(fn, formal_vars):
    """Run ``fn`` on ``formal_vars`` with ops captured into a new block."""
    prog = default_main_program()
    blk = prog._create_block()
    # formals were created by the caller in blk already
    with block_guard(blk):
        outs = fn(*formal_vars)
    return blk, outs


def _collect_captures(program, block_idxs, exclude):
    """Names read by the sub-blocks (recursively) that resolve outside them.

    These become explicit inputs of the control-flow op so static autodiff
    sees the dependency (e.g. RNN weights used inside a scan body).
    """
    captures = []
    seen = set(exclude)
    stack = list(block_idxs)
    local_blocks = set(block_idxs)
    while stack:
        bi = stack.pop()
        blk = program.blocks[bi]
        for op in blk.ops:
            for key, val in op.attrs.items():
                if key in BLOCK_ATTR_KEYS and isinstance(val, int):
                    local_blocks.add(val)
                    stack.append(val)
            for names in op.inputs.values():
                for n in names:
                    if n in seen:
                        continue
                    seen.add(n)
                    owner = _owning_block(program, blk, n)
                    if owner is not None and owner.idx not in local_blocks:
                        captures.append(n)
    return captures


def _owning_block(program, block, name):
    blk = block
    while blk is not None:
        if name in blk.vars:
            return blk
        blk = program.blocks[blk.parent_idx] if blk.parent_idx >= 0 else None
    return None


def _defined_names(program, block_idxs):
    names = set()
    for bi in block_idxs:
        names.update(program.blocks[bi].vars.keys())
    return names


def while_loop(cond, body, loop_vars, is_test=False, name=None,
               max_iters=None):
    """paddle.static.nn.while_loop (fluid control_flow.py while_loop).

    ``cond(*loop_vars) -> bool scalar``, ``body(*loop_vars) -> loop_vars'``.
    Lowers to ``lax.while_loop``; loop-carried shapes/dtypes must be
    invariant.

    Differentiability: an unbounded while has no reverse mode on XLA
    (lax.while_loop has no VJP). Pass ``max_iters=N`` to lower the loop to
    a masked :func:`scan` of exactly N steps — each step runs the body
    under ``cond(*vars)`` and passes the carry through unchanged once the
    condition turns false — which IS reverse-differentiable, matching the
    reference's trainable while
    (/root/reference/paddle/fluid/operators/controlflow/while_op.cc grad
    maker). The masked form always runs N steps, so pick the tightest
    bound you can.
    """
    if max_iters is not None:
        return _bounded_while(cond, body, loop_vars, int(max_iters))
    loop_vars = _as_variables(loop_vars)
    if not loop_vars:
        raise ValueError("while_loop needs at least one loop variable")
    prog = default_main_program()
    parent = prog.current_block()

    cond_blk = prog._create_block()
    cond_formals = _placeholders(cond_blk, loop_vars)
    with block_guard(cond_blk):
        pred = cond(*cond_formals)
    if isinstance(pred, (list, tuple)):
        raise TypeError("while_loop cond must return a single boolean")

    body_blk = prog._create_block()
    body_formals = _placeholders(body_blk, loop_vars)
    with block_guard(body_blk):
        body_outs = _as_list(body(*body_formals))
    if len(body_outs) != len(loop_vars):
        raise ValueError(
            f"body returned {len(body_outs)} vars, expected {len(loop_vars)}"
        )

    formal_names = [v.name for v in cond_formals] + [v.name for v in body_formals]
    captures = _collect_captures(
        prog, [cond_blk.idx, body_blk.idx], set(formal_names)
    )

    out_vars = []
    for v in loop_vars:
        ov = parent.create_var(
            name=prog._unique_name("while_out"), shape=v.shape,
            dtype=str(v.dtype),
        )
        ov.stop_gradient = True  # while is not reverse-differentiable
        out_vars.append(ov)

    parent.append_op(
        "while",
        {"X": [v.name for v in loop_vars] + captures},
        {"Out": [v.name for v in out_vars]},
        {
            "__cond_block__": cond_blk.idx,
            "__body_block__": body_blk.idx,
            "__cond_formals__": [v.name for v in cond_formals],
            "__body_formals__": [v.name for v in body_formals],
            "__cond_out__": pred.name,
            "__body_outs__": [v.name for v in body_outs],
            "__n_loop__": len(loop_vars),
            "is_test": is_test,
        },
    )
    return out_vars


def _bounded_while(cond_fn, body_fn, loop_vars, max_iters):
    """while(cond) with a trip-count bound: a scan of ``max_iters`` steps
    whose body is ``cond(vars) ? body(vars) : vars`` — the differentiable
    lowering behind ``while_loop(max_iters=...)``."""
    loop_vars = _as_variables(loop_vars)

    def sbody(*carries):
        pred = cond_fn(*carries)
        outs = cond(
            pred,
            lambda: _as_list(body_fn(*carries)),
            lambda: list(carries),
        )
        return _as_list(outs), []

    finals, _ = scan(sbody, list(loop_vars), None, length=max_iters)
    return finals


def cond(pred, true_fn, false_fn, name=None):
    """paddle.static.nn.cond (conditional_block_op pair + select).

    Both branches are traced into sub-blocks and compiled; ``lax.cond``
    selects at run time. Differentiable.
    """
    prog = default_main_program()
    parent = prog.current_block()

    true_blk = prog._create_block()
    with block_guard(true_blk):
        t_outs = _as_list(true_fn())
    false_blk = prog._create_block()
    with block_guard(false_blk):
        f_outs = _as_list(false_fn())
    if len(t_outs) != len(f_outs):
        raise ValueError(
            f"cond branches returned {len(t_outs)} vs {len(f_outs)} outputs"
        )
    for i, (t, f) in enumerate(zip(t_outs, f_outs)):
        if str(t.dtype) != str(f.dtype):
            raise TypeError(
                f"cond branch dtype mismatch: {t.dtype} vs {f.dtype}"
            )
        # shape check at build time: a mismatch would otherwise surface
        # as an opaque lax.cond XLA error at exe.run
        ts, fs = t.shape, f.shape
        if ts is not None and fs is not None and list(ts) != list(fs):
            raise ValueError(
                f"cond branch output {i} shape mismatch: true_fn returned "
                f"{list(ts)}, false_fn returned {list(fs)} — both branches "
                "must produce identically-shaped outputs (lax.cond)"
            )

    captures = _collect_captures(prog, [true_blk.idx, false_blk.idx], set())

    out_vars = []
    for t, f in zip(t_outs, f_outs):
        ov = parent.create_var(
            name=prog._unique_name("cond_out"), shape=t.shape,
            dtype=str(t.dtype),
        )
        ov.stop_gradient = t.stop_gradient and f.stop_gradient
        out_vars.append(ov)

    parent.append_op(
        "cond",
        {"X": [pred.name] + captures},
        {"Out": [v.name for v in out_vars]},
        {
            "__true_block__": true_blk.idx,
            "__false_block__": false_blk.idx,
            "__true_outs__": [v.name for v in t_outs],
            "__false_outs__": [v.name for v in f_outs],
        },
    )
    return out_vars[0] if len(out_vars) == 1 else out_vars


def scan(body, init, sequences=None, length=None, name=None):
    """Differentiable bounded loop over time-major sequences (TPU-native).

    ``body(*carries, *x_slices) -> (new_carries, y_slices)`` where
    ``x_slices`` are per-step slices (``seq[t]``) of each sequence and
    ``y_slices`` are per-step outputs stacked into ``[T, ...]`` results.
    Returns ``(final_carries, stacked_ys)`` (each a list).

    This is the construct to train RNN-style models with: it lowers to
    ``lax.scan``, which XLA reverse-differentiates (the role of the
    reference's recurrent_op / StaticRNN, fluid/layers/control_flow.py).
    """
    init = _as_variables(init)
    sequences = _as_variables(sequences)
    if not init and not sequences:
        raise ValueError("scan needs carries and/or sequences")
    if not sequences and length is None:
        raise ValueError(
            "scan without sequences needs an explicit length= (static trip "
            "count; XLA loops are bounded)"
        )
    prog = default_main_program()
    parent = prog.current_block()

    body_blk = prog._create_block()
    carry_formals = _placeholders(body_blk, init, prefix="scan_carry")
    seq_formals = _placeholders(
        body_blk, sequences,
        shapes=[list(s.shape)[1:] for s in sequences], prefix="scan_x",
    )
    with block_guard(body_blk):
        res = body(*carry_formals, *seq_formals)
    if not (isinstance(res, tuple) and len(res) == 2):
        raise TypeError(
            "scan body must return (new_carries, y_slices); use ([], ...) "
            "or (..., []) for empty groups"
        )
    new_carries, ys = _as_list(res[0]), _as_list(res[1])
    if len(new_carries) != len(init):
        raise ValueError(
            f"body returned {len(new_carries)} carries, expected {len(init)}"
        )

    formal_names = {v.name for v in carry_formals} | {v.name for v in seq_formals}
    captures = _collect_captures(prog, [body_blk.idx], formal_names)

    length = sequences[0].shape[0] if sequences else int(length)

    out_vars = []
    for v in new_carries:
        ov = parent.create_var(
            name=prog._unique_name("scan_carry_out"), shape=v.shape,
            dtype=str(v.dtype),
        )
        ov.stop_gradient = v.stop_gradient
        out_vars.append(ov)
    for v in ys:
        ov = parent.create_var(
            name=prog._unique_name("scan_y"),
            shape=[length] + list(v.shape or []),
            dtype=str(v.dtype),
        )
        ov.stop_gradient = v.stop_gradient
        out_vars.append(ov)

    parent.append_op(
        "scan",
        {"X": [v.name for v in init] + [v.name for v in sequences] + captures},
        {"Out": [v.name for v in out_vars]},
        {
            "__body_block__": body_blk.idx,
            "__carry_formals__": [v.name for v in carry_formals],
            "__seq_formals__": [v.name for v in seq_formals],
            "__carry_outs__": [v.name for v in new_carries],
            "__y_outs__": [v.name for v in ys],
            "__n_carry__": len(init),
            "__n_seq__": len(sequences),
            "__length__": None if sequences else length,
        },
    )
    n_c = len(init)
    return out_vars[:n_c], out_vars[n_c:]


def case(pred_fn_pairs, default=None, name=None):
    """fluid.layers.case: first true predicate wins. Built on cond chains."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if rest:
        return cond(pred, fn, lambda: case(rest, default=default))
    if default is not None:
        return cond(pred, fn, default)
    return fn()


def switch_case(branch_index, branch_fns, default=None, name=None):
    """fluid.layers.switch_case over an integer index."""
    from .. import ops

    items = sorted(branch_fns.items()) if isinstance(branch_fns, dict) else list(
        enumerate(branch_fns)
    )
    pairs = [
        (ops.equal(branch_index, np.int64(i)), fn) for i, fn in items
    ]
    if default is None:
        default = items[-1][1]
    return case(pairs, default=default)
