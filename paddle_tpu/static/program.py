"""Static-graph Program IR.

Reference parity: paddle/fluid/framework/framework.proto:212 (ProgramDesc →
BlockDesc → OpDesc/VarDesc) and python/paddle/fluid/framework.py (Program/
Block/Variable). TPU-native: the IR is the unit of *capture*, not of
interpretation — the Executor lowers a whole block to one jax.jit'd XLA
module (SURVEY.md §7 step 2), so OpDesc stays lightweight (type, name-keyed
io maps, attrs) and per-op kernels are the registry's pure JAX functions.
Serialization via to_dict/from_dict + json (framework.proto equivalent).
"""
from __future__ import annotations

import contextlib
import itertools
import json
from typing import Any, Dict, List

import numpy as np

from ..framework.dtype import convert_dtype, dtype_name
from ..framework.tensor import Tensor


class VarDesc:
    def __init__(self, name, shape=None, dtype="float32", persistable=False,
                 stop_gradient=True, is_data=False):
        self.name = name
        self.shape = list(shape) if shape is not None else None
        self.dtype = dtype_name(convert_dtype(dtype))
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data

    def to_dict(self):
        return dict(name=self.name, shape=self.shape, dtype=self.dtype,
                    persistable=self.persistable, stop_gradient=self.stop_gradient,
                    is_data=self.is_data)

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


class OpDesc:
    """type + name-keyed input/output lists + attrs (framework.proto:42)."""

    def __init__(self, op_type: str, inputs: Dict[str, List[str]],
                 outputs: Dict[str, List[str]], attrs: Dict[str, Any]):
        self.type = op_type
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = dict(attrs)

    def input_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    def output_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def to_dict(self):
        attrs = {}
        for k, v in self.attrs.items():
            if isinstance(v, np.ndarray):
                attrs[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
            else:
                attrs[k] = v
        return dict(type=self.type, inputs=self.inputs, outputs=self.outputs, attrs=attrs)

    @classmethod
    def from_dict(cls, d):
        attrs = {}
        for k, v in d["attrs"].items():
            if isinstance(v, dict) and "__ndarray__" in v:
                attrs[k] = np.asarray(v["__ndarray__"], dtype=v["dtype"])
            else:
                attrs[k] = v
        return cls(d["type"], d["inputs"], d["outputs"], attrs)


class Block:
    """BlockDesc (framework.proto:174): ordered op list + var map."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[OpDesc] = []

    # -- var management -----------------------------------------------------
    def create_var(self, name=None, shape=None, dtype="float32", persistable=False,
                   stop_gradient=True, is_data=False):
        name = name or self.program._unique_name("tmp")
        var = Variable(self, name, shape, dtype, persistable, stop_gradient, is_data)
        self.vars[name] = var
        return var

    def create_parameter(self, name, shape, dtype="float32", initializer=None,
                         trainable=True):
        var = self.create_var(name=name, shape=shape, dtype=dtype, persistable=True,
                              stop_gradient=not trainable)
        var.is_parameter = True
        var.initializer = initializer
        return var

    def var(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = self.program.blocks[blk.parent_idx] if blk.parent_idx >= 0 else None
        raise KeyError(f"variable {name!r} not found in block {self.idx}")

    def has_var(self, name):
        try:
            self.var(name)
            return True
        except KeyError:
            return False

    def append_op(self, op_type, inputs, outputs, attrs=None):
        op = OpDesc(op_type, inputs, outputs, attrs or {})
        self.ops.append(op)
        self.program._version += 1
        return op

    def to_dict(self):
        return dict(
            idx=self.idx,
            parent_idx=self.parent_idx,
            vars=[v.desc_dict() for v in self.vars.values()],
            ops=[op.to_dict() for op in self.ops],
        )


class Variable(Tensor):
    """Symbolic variable in a Block (fluid/framework.py Variable).

    Inherits Tensor so the whole mode-aware ops API (paddle_tpu.ops.*) can
    operate on it; storage-dependent members are overridden to be symbolic.
    """

    __slots__ = ("_meta",)

    def __init__(self, block, name, shape, dtype, persistable, stop_gradient, is_data):
        # No storage: bypass Tensor.__init__ entirely.
        self._array = None
        self.grad = None
        self.persistable = persistable
        self.name = name
        self._node = None
        self._out_index = 0
        self.stop_gradient = stop_gradient
        self._meta = dict(
            block=block, shape=list(shape) if shape is not None else None,
            dtype=dtype_name(convert_dtype(dtype)), is_data=is_data,
            is_parameter=False, initializer=None,
        )

    # symbolic metadata accessors -------------------------------------------
    @property
    def block(self):
        return self._meta["block"]

    @property
    def shape(self):
        return self._meta["shape"]

    @property
    def dtype(self):
        import jax.numpy as jnp

        return jnp.dtype(self._meta["dtype"])

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        return int(np.prod([d for d in self.shape])) if self.shape else 1

    @property
    def is_parameter(self):
        return self._meta["is_parameter"]

    @is_parameter.setter
    def is_parameter(self, v):
        self._meta["is_parameter"] = v

    @property
    def initializer(self):
        return self._meta["initializer"]

    @initializer.setter
    def initializer(self, v):
        self._meta["initializer"] = v

    def desc_dict(self):
        m = self._meta
        return VarDesc(self.name, m["shape"], m["dtype"], self.persistable,
                       self.stop_gradient, m["is_data"]).to_dict()

    # storage-dependent methods are invalid symbolically --------------------
    def numpy(self):
        raise RuntimeError(
            f"Variable {self.name!r} is symbolic; run it through an Executor to get values"
        )

    def item(self):
        raise RuntimeError("symbolic Variable has no value")

    def set_value(self, value):
        from .executor import global_scope

        arr = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
        global_scope().set(self.name, arr)

    def get_value(self):
        from .executor import global_scope

        return Tensor(global_scope().get(self.name))

    def backward(self, *a, **k):
        raise RuntimeError("call paddle_tpu.static.append_backward on the loss instead")

    def __repr__(self):
        m = self._meta
        return f"Variable(name={self.name}, shape={m['shape']}, dtype={m['dtype']})"

    def __hash__(self):
        return id(self)


_program_token_counter = itertools.count()


class Program:
    """ProgramDesc (framework.proto:212)."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self._name_counter = {}
        self._version = 0
        self.random_seed = None
        # process-unique identity for executor compile caching: id() can
        # be reused after GC, silently aliasing two programs at the same
        # version in the cache
        self._identity_token = next(_program_token_counter)

    def global_block(self) -> Block:
        return self.blocks[0]

    def verify(self, feed_names=(), fetch_list=(), level="on"):
        """Run the program-IR verifier (analysis/) over this program.

        Returns the :class:`~paddle_tpu.analysis.VerifyReport` when the
        program is well-formed (possibly carrying warnings); raises
        :class:`~paddle_tpu.analysis.VerifyError` naming the offending
        block/op index/op type/var otherwise. ``level="strict"``
        additionally promotes dead-code findings to errors.

        The verdict is cached per (program version, feeds, fetches,
        level) — any mutation through ``append_op``/``_create_block``
        bumps ``_version`` and re-verifies — so ``Executor.run``'s
        automatic call (``FLAGS_program_verify``) costs one dict lookup
        in steady state (bench.py ``executor_dispatch.program_verify``).
        """
        fetch_names = tuple(
            v if isinstance(v, str) else v.name for v in (fetch_list or ()))
        # var-count fingerprint: create_var does NOT bump _version (only
        # append_op/_create_block do), but adding a var can flip a verify
        # verdict — e.g. declaring the persistable a cached VerifyError
        # complained about. len(dict) is O(1), so this stays a few ns per
        # block. (A persistable-flag flip on an EXISTING var remains
        # invisible — the same documented blind spot as RunPlan's.)
        n_vars = sum(len(b.vars) for b in self.blocks)
        feeds = tuple(sorted(feed_names or ()))
        key = (self._version, n_vars, feeds, fetch_names, level)
        # __dict__ access: from_dict builds programs via __new__, so the
        # cache attr may not exist yet
        cache = self.__dict__.setdefault("_verify_cache", {})
        hit = cache.get(key)
        if hit is not None:
            # LRU refresh: without it a rotation of >capacity distinct
            # feed/fetch combos FIFO-thrashes and re-runs the full pass
            # (~ms) on every dispatch
            cache.pop(key, None)
            cache[key] = hit
            if isinstance(hit, Exception):
                # fresh traceback each raise: re-raising the cached
                # instance as-is would append frames to its __traceback__
                # forever (and share the mutable chain across threads)
                raise hit.with_traceback(None)
            return hit
        from ..analysis import VerifyError, verify_program

        try:
            report = verify_program(self, feeds, fetch_names, level)
        except VerifyError as e:
            self._verify_record(key, error=e)
            raise
        self._verify_record(key, report=report)
        return report

    def _verify_record(self, key, report=None, error=None):
        """Cache a verification verdict (bounded) + flight breadcrumb."""
        cache = self.__dict__.setdefault("_verify_cache", {})
        cache[key] = error if error is not None else report
        # LRU-bounded (hits move-to-end above); entries are small reports,
        # so the bound covers a predictor serving many fetch subsets
        while len(cache) > 64:
            # replica pools verify from N threads: a concurrent evict of
            # the same oldest key must be a no-op, not a KeyError
            try:
                cache.pop(next(iter(cache)), None)
            except (StopIteration, RuntimeError):
                break
        try:  # the black box must never break verification itself
            from ..monitor import flight_recorder as _flight

            tok = getattr(self, "_identity_token", None)
            fields = dict(
                program=f"{tok if tok is not None else id(self)}@v{key[0]}",
                ok=error is None,
                warnings=len(report.warnings) if report is not None else 0,
            )
            if error is not None:
                fields["error"] = str(error)[:500]
            _flight.record_event("program_verify", **fields)
        except Exception:
            pass

    def plan_memory(self, feed_names=(), fetch_list=(), feed_shapes=None,
                    top_k=8):
        """Static liveness + peak-HBM plan for this program
        (:func:`paddle_tpu.analysis.plan_memory`): predicted peak
        resident bytes, the high-water op index, the per-op resident
        curve, and the top-K largest live tensors — computed from the
        IR alone, before any lowering. ``feed_shapes`` (``{name: shape
        tuple}``) concretizes ``-1`` batch dims. ``Executor.run``
        enforces the device HBM budget against this plan behind
        ``FLAGS_memory_budget_check``."""
        from ..analysis import plan_memory as _plan

        fetch_names = tuple(
            v if isinstance(v, str) else v.name for v in (fetch_list or ()))
        return _plan(self, tuple(feed_names or ()), fetch_names,
                     feed_shapes=feed_shapes, top_k=top_k)

    def current_block(self) -> Block:
        return self.blocks[_current_block_idx[-1]] if _current_block_idx else self.blocks[0]

    def _create_block(self, parent_idx=None) -> Block:
        """New nested block (BlockDesc with parent, framework.proto:174) —
        the unit consumed by control-flow ops (while/cond/scan)."""
        parent = self.current_block().idx if parent_idx is None else parent_idx
        blk = Block(self, len(self.blocks), parent)
        self.blocks.append(blk)
        self._version += 1
        return blk

    def _unique_name(self, prefix):
        i = self._name_counter.get(prefix, 0)
        self._name_counter[prefix] = i + 1
        return f"{prefix}_{i}"

    def list_vars(self):
        for blk in self.blocks:
            yield from blk.vars.values()

    def all_parameters(self):
        return [v for v in self.list_vars() if getattr(v, "is_parameter", False)]

    def clone(self, for_test=False):
        data = self.to_dict()
        prog = Program.from_dict(data)
        if for_test:
            for blk in prog.blocks:
                for op in blk.ops:
                    if "training" in op.attrs:
                        op.attrs["training"] = False
        prog._name_counter = dict(self._name_counter)
        return prog

    # serialization ---------------------------------------------------------
    def to_dict(self):
        d = dict(blocks=[b.to_dict() for b in self.blocks], version=1)
        consts = getattr(self, "_constants", None)
        if consts:
            # captured eager constants (op_append.capture_constant) are part
            # of the program's meaning — without them a deserialized
            # program cannot run (every numpy literal in a control-flow
            # body becomes one)
            d["constants"] = {
                k: {"__ndarray__": np.asarray(v).tolist(),
                    "dtype": str(np.asarray(v).dtype)}
                for k, v in consts.items()
            }
        return d

    @classmethod
    def from_dict(cls, data):
        prog = cls.__new__(cls)
        prog.blocks = []
        prog._name_counter = {}
        prog._version = 0
        prog.random_seed = None
        for bd in data["blocks"]:
            blk = Block(prog, bd["idx"], bd["parent_idx"])
            prog.blocks.append(blk)
            for vd in bd["vars"]:
                v = VarDesc.from_dict(vd)
                var = Variable(blk, v.name, v.shape, v.dtype, v.persistable,
                               v.stop_gradient, v.is_data)
                blk.vars[v.name] = var
            blk.ops = [OpDesc.from_dict(od) for od in bd["ops"]]
        if data.get("constants"):
            prog._constants = {
                k: np.asarray(v["__ndarray__"], dtype=v["dtype"])
                for k, v in data["constants"].items()
            }
        return prog

    def serialize_to_string(self) -> bytes:
        return json.dumps(self.to_dict()).encode()

    @classmethod
    def parse_from_string(cls, s: bytes):
        return cls.from_dict(json.loads(s.decode()))

    def __repr__(self):
        n_ops = sum(len(b.ops) for b in self.blocks)
        return f"Program(blocks={len(self.blocks)}, ops={n_ops})"


# -- global default/startup programs + guards (fluid/framework.py) ----------

_default_main_program = Program()
_default_startup_program = Program()
_current_block_idx: list = []
_static_mode = [False]


def default_main_program() -> Program:
    return _default_main_program


def default_startup_program() -> Program:
    return _default_startup_program


def reset_default_programs():
    global _default_main_program, _default_startup_program
    _default_main_program = Program()
    _default_startup_program = Program()


@contextlib.contextmanager
def block_guard(block):
    """Make ``block`` the current append target (control-flow sub-blocks)."""
    _current_block_idx.append(block.idx)
    try:
        yield block
    finally:
        _current_block_idx.pop()


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _default_main_program, _default_startup_program
    prev_main, prev_startup = _default_main_program, _default_startup_program
    _default_main_program = main_program
    if startup_program is not None:
        _default_startup_program = startup_program
    try:
        yield
    finally:
        _default_main_program, _default_startup_program = prev_main, prev_startup


def enable_static():
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


def in_static_mode() -> bool:
    return _static_mode[0]


def in_dynamic_mode() -> bool:
    return not _static_mode[0]


def data(name, shape, dtype="float32", lod_level=0):
    """paddle.static.data — declare a feed variable."""
    blk = default_main_program().global_block()
    var = blk.create_var(name=name, shape=shape, dtype=dtype, is_data=True)
    var.stop_gradient = True
    return var
