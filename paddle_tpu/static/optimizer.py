"""Static-graph optimizers.

Reference parity: python/paddle/fluid/optimizer.py:56 — minimize() appends
backward + parameter-update ops to the program (operators/optimizers/*.cc
equivalents are the *_update kernels in ops/kernels.py). The learning rate
is a persistable scalar in the scope (a traced input), so host-side LR
schedules never retrigger XLA compilation.
"""
from __future__ import annotations

import numpy as np

from .backward import append_backward
from .executor import global_scope
from .nn import create_parameter
from .program import default_main_program
from ..nn import initializer as I


class StaticOptimizer:
    def __init__(self, learning_rate=0.001, grad_clip=None):
        self._lr = learning_rate
        self._grad_clip = grad_clip
        self._lr_name = None

    def _lr_var(self, prog):
        if self._lr_name is None:
            var = create_parameter([], "float32", name=prog._unique_name("learning_rate"),
                                   initializer=I.Constant(self._get_lr_value()),
                                   trainable=False)
            var.stop_gradient = True
            self._lr_name = var.name
        return prog.global_block().var(self._lr_name)

    def _get_lr_value(self):
        lr = self._lr
        return float(lr() if callable(lr) else lr)

    def set_lr(self, value):
        self._lr = float(value)
        if self._lr_name is not None and global_scope().has(self._lr_name):
            global_scope().set(self._lr_name, np.float32(value))

    def sync_lr(self):
        """Push the current (possibly scheduled) lr into the scope."""
        if self._lr_name is not None:
            global_scope().set(self._lr_name, np.float32(self._get_lr_value()))

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        prog = default_main_program()
        params_grads = append_backward(loss, parameter_list, no_grad_set)
        if self._grad_clip is not None:
            params_grads = self._append_clip(prog, params_grads)
        lr = self._lr_var(prog)
        self._append_update_ops(prog, params_grads, lr)
        return None, params_grads

    def _append_clip(self, prog, params_grads):
        # ClipGradByGlobalNorm-style clipping as graph ops
        from .. import ops

        grads = [g for _, g in params_grads]
        sq = None
        for g in grads:
            s = ops.sum(ops.square(g))
            sq = s if sq is None else sq + s
        gnorm = ops.sqrt(sq)
        clip_norm = self._grad_clip.clip_norm
        factor = ops.minimum(
            ops.full([], 1.0), ops.full([], float(clip_norm)) / ops.maximum(
                gnorm, ops.full([], 1e-12)))
        return [(p, g * factor) for p, g in params_grads]

    def _append_update_ops(self, prog, params_grads, lr):
        raise NotImplementedError


class SGD(StaticOptimizer):
    def _append_update_ops(self, prog, params_grads, lr):
        block = prog.global_block()
        for p, g in params_grads:
            # __inplace__: the update op writes the param it reads — the
            # declared aliasing the verifier's write-conflicts pass wants
            block.append_op("sgd", {"X": [p.name, g.name, lr.name]},
                            {"Out": [p.name]}, {"__inplace__": [p.name]})


class Momentum(StaticOptimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, use_nesterov=False,
                 grad_clip=None):
        super().__init__(learning_rate, grad_clip)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _append_update_ops(self, prog, params_grads, lr):
        block = prog.global_block()
        for p, g in params_grads:
            vel = create_parameter(p.shape, str(p.dtype), name=p.name + "@velocity",
                                   initializer=I.Constant(0.0), trainable=False)
            block.append_op(
                "momentum_update",
                {"X": [p.name, g.name, vel.name, lr.name]},
                {"Out": [p.name, vel.name]},
                {"mu": self._momentum, "use_nesterov": self._use_nesterov,
                 "__inplace__": [p.name, vel.name]})


class Adam(StaticOptimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 grad_clip=None):
        super().__init__(learning_rate, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _append_update_ops(self, prog, params_grads, lr):
        block = prog.global_block()
        step = create_parameter([], "float32", name=prog._unique_name("adam_step"),
                                initializer=I.Constant(0.0), trainable=False)
        step.stop_gradient = True
        block.append_op("increment", {"X": [step.name]}, {"Out": [step.name]},
                        {"value": 1.0, "__inplace__": [step.name]})
        for p, g in params_grads:
            m1 = create_parameter(p.shape, str(p.dtype), name=p.name + "@moment1",
                                  initializer=I.Constant(0.0), trainable=False)
            m2 = create_parameter(p.shape, str(p.dtype), name=p.name + "@moment2",
                                  initializer=I.Constant(0.0), trainable=False)
            block.append_op(
                "adam_update",
                {"X": [p.name, g.name, m1.name, m2.name, lr.name, step.name]},
                {"Out": [p.name, m1.name, m2.name]},
                {"beta1": self._beta1, "beta2": self._beta2,
                 "epsilon": self._epsilon,
                 "__inplace__": [p.name, m1.name, m2.name]})
