"""Static-graph layer helpers.

Reference parity: python/paddle/fluid/layers/nn.py (fc, conv2d, …) via
LayerHelper (fluid/layer_helper.py): create parameter vars + append ops.
Most of fluid.layers is covered by the mode-aware paddle_tpu.ops API; these
helpers add the parameter-creating layers.
"""
from __future__ import annotations

from .. import ops
from ..nn import initializer as I
from .program import default_main_program, default_startup_program


def create_parameter(shape, dtype="float32", name=None, initializer=None,
                     is_bias=False, trainable=True):
    prog = default_main_program()
    block = prog.global_block()
    name = name or prog._unique_name("param")
    init = I._resolve(initializer, is_bias=is_bias)
    var = block.create_parameter(name, shape, dtype, initializer=init,
                                 trainable=trainable)
    sblock = default_startup_program().global_block()
    sblock.append_op("init_param", {"X": []}, {"Out": [name]},
                     {"initializer": init, "shape": list(shape), "dtype": dtype})
    return var


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None, activation=None,
       name=None):
    """fluid.layers.fc (fluid/layers/nn.py) — flatten + mul + bias + act."""
    in_features = 1
    for d in x.shape[num_flatten_dims:]:
        in_features *= d
    w = create_parameter([in_features, size], str(x.dtype), initializer=weight_attr)
    out = ops.mul(x, w, x_num_col_dims=num_flatten_dims)
    if bias_attr is not False:
        b = create_parameter([size], str(x.dtype), initializer=bias_attr, is_bias=True)
        out = ops.add(out, b)
    if activation:
        out = getattr(ops, activation)(out)
    return out


def conv2d(x, num_filters, filter_size, stride=1, padding=0, dilation=1, groups=1,
           weight_attr=None, bias_attr=None, activation=None, name=None):
    ks = filter_size if isinstance(filter_size, (list, tuple)) else (filter_size, filter_size)
    in_channels = x.shape[1]
    fan_in = in_channels // groups * ks[0] * ks[1]
    w = create_parameter(
        [num_filters, in_channels // groups, ks[0], ks[1]], str(x.dtype),
        initializer=weight_attr or I.KaimingUniform(fan_in=fan_in))
    out = ops.conv2d(x, w, None, stride=stride, padding=padding,
                     dilation=dilation, groups=groups)
    if bias_attr is not False:
        b = create_parameter([num_filters], str(x.dtype), initializer=bias_attr, is_bias=True)
        out = ops.add(out, ops.reshape(b, [1, num_filters, 1, 1]))
    if activation:
        out = getattr(ops, activation)(out)
    return out


def batch_norm(x, momentum=0.9, epsilon=1e-5, weight_attr=None, bias_attr=None,
               is_test=False, name=None):
    c = x.shape[1]
    scale = create_parameter([c], str(x.dtype), initializer=weight_attr or I.Constant(1.0))
    bias = create_parameter([c], str(x.dtype), initializer=bias_attr, is_bias=True)
    mean = create_parameter([c], str(x.dtype), initializer=I.Constant(0.0), trainable=False)
    var = create_parameter([c], str(x.dtype), initializer=I.Constant(1.0), trainable=False)
    mean.stop_gradient = True
    var.stop_gradient = True
    return ops.batch_norm(x, mean, var, scale, bias, training=not is_test,
                          momentum=momentum, epsilon=epsilon)


def embedding(x, size, padding_idx=None, weight_attr=None, name=None):
    w = create_parameter(list(size), "float32",
                         initializer=weight_attr or I.Normal(0.0, 1.0))
    return ops.embedding(x, w, padding_idx=padding_idx)


def layer_norm(x, begin_norm_axis=-1, epsilon=1e-5, weight_attr=None, bias_attr=None):
    if begin_norm_axis < 0:
        begin_norm_axis = len(x.shape) + begin_norm_axis
    shape = list(x.shape[begin_norm_axis:])
    scale = create_parameter(shape, str(x.dtype), initializer=weight_attr or I.Constant(1.0))
    bias = create_parameter(shape, str(x.dtype), initializer=bias_attr, is_bias=True)
    return ops.layer_norm(x, shape, scale, bias, epsilon)


def dropout(x, dropout_prob=0.5, is_test=False):
    return ops.dropout(x, p=dropout_prob, training=not is_test)


# -- recurrent front end ------------------------------------------------------
# fluid/layers/rnn.py lstm/dynamic_gru + StaticRNN — lowered to the scan
# construct (lax.scan), which XLA reverse-differentiates; weights follow
# the single-matmul-per-gate-block layout the MXU wants.


def _recurrent(x, init_states, hidden_size, n_gates, step, time_major,
               init_of):
    """Shared scan driver: x [B,T,D] (or [T,B,D]), per-step ``step``."""
    from .control_flow import scan

    if not time_major:
        x = ops.transpose(x, [1, 0, 2])  # [T, B, D]
    in_dim = x.shape[2]
    w_ih = create_parameter([in_dim, n_gates * hidden_size], str(x.dtype))
    w_hh = create_parameter([hidden_size, n_gates * hidden_size],
                            str(x.dtype))
    b = create_parameter([n_gates * hidden_size], str(x.dtype), is_bias=True)

    if init_states is None:
        batch = x.shape[1]
        if batch in (-1, None):
            raise ValueError(
                "recurrent layers need either a static batch dim or "
                "explicit initial states (XLA carries are fixed-shape)"
            )
        init_states = init_of(batch)

    def cell(*args):
        states, xt = list(args[:-1]), args[-1]
        gates = ops.add(
            ops.add(ops.matmul(xt, w_ih), ops.matmul(states[0], w_hh)), b
        )
        new_states = step(states, gates)
        return new_states, [new_states[0]]

    finals, ys = scan(cell, init_states, [x])
    out = ys[0]  # [T, B, H]
    if not time_major:
        out = ops.transpose(out, [1, 0, 2])
    return out, finals


def simple_rnn(x, hidden_size, init_h=None, time_major=False, name=None):
    """Elman RNN over scan (StaticRNN/recurrent_op capability,
    fluid/layers/control_flow.py StaticRNN). Returns (out, [h_T])."""

    def step(states, gates):
        return [ops.tanh(gates)]

    return _recurrent(
        x, [init_h] if init_h is not None else None, hidden_size, 1, step,
        time_major,
        lambda b: [ops.zeros([b, hidden_size], str(x.dtype))],
    )


def lstm(x, hidden_size, init_h=None, init_c=None, time_major=False,
         name=None):
    """fluid.layers.lstm (fluid/layers/rnn.py) — (out, [h_T, c_T])."""

    def step(states, gates):
        h, c = states
        i, f, g, o = ops.split(gates, 4, axis=-1)
        c2 = ops.add(
            ops.multiply(ops.sigmoid(f), c),
            ops.multiply(ops.sigmoid(i), ops.tanh(g)),
        )
        h2 = ops.multiply(ops.sigmoid(o), ops.tanh(c2))
        return [h2, c2]

    inits = None
    if init_h is not None and init_c is not None:
        inits = [init_h, init_c]
    return _recurrent(
        x, inits, hidden_size, 4, step, time_major,
        lambda b: [ops.zeros([b, hidden_size], str(x.dtype)),
                   ops.zeros([b, hidden_size], str(x.dtype))],
    )


def gru(x, hidden_size, init_h=None, time_major=False, name=None):
    """fluid.layers.dynamic_gru capability — (out, [h_T]).

    Gate math follows the standard GRU; the candidate's recurrent term is
    computed on the reset-scaled state (the reference's default mode).
    """
    from .control_flow import scan

    if not time_major:
        x = ops.transpose(x, [1, 0, 2])
    in_dim = x.shape[2]
    H = hidden_size
    w_ih = create_parameter([in_dim, 3 * H], str(x.dtype))
    w_hh_rz = create_parameter([H, 2 * H], str(x.dtype))
    w_hh_c = create_parameter([H, H], str(x.dtype))
    b = create_parameter([3 * H], str(x.dtype), is_bias=True)

    if init_h is None:
        batch = x.shape[1]
        if batch in (-1, None):
            raise ValueError(
                "gru needs a static batch dim or explicit init_h"
            )
        init_h = ops.zeros([batch, H], str(x.dtype))

    def cell(h, xt):
        xg = ops.add(ops.matmul(xt, w_ih), b)  # [B, 3H]
        x_rz = ops.slice(xg, [1], [0], [2 * H])
        x_c = ops.slice(xg, [1], [2 * H], [3 * H])
        rz = ops.sigmoid(ops.add(x_rz, ops.matmul(h, w_hh_rz)))
        r = ops.slice(rz, [1], [0], [H])
        z = ops.slice(rz, [1], [H], [2 * H])
        cand = ops.tanh(
            ops.add(x_c, ops.matmul(ops.multiply(r, h), w_hh_c))
        )
        h2 = ops.add(
            ops.multiply(z, h),
            ops.multiply(ops.subtract(ops.full([], 1.0), z), cand),
        )
        return [h2], [h2]

    finals, ys = scan(cell, [init_h], [x])
    out = ys[0]
    if not time_major:
        out = ops.transpose(out, [1, 0, 2])
    return out, finals


# -- control flow (operators/controlflow/, fluid/layers/control_flow.py) -----
from .control_flow import (  # noqa: E402,F401
    case,
    cond,
    scan,
    switch_case,
    while_loop,
)
