"""Static-graph layer helpers.

Reference parity: python/paddle/fluid/layers/nn.py (fc, conv2d, …) via
LayerHelper (fluid/layer_helper.py): create parameter vars + append ops.
Most of fluid.layers is covered by the mode-aware paddle_tpu.ops API; these
helpers add the parameter-creating layers.
"""
from __future__ import annotations

from .. import ops
from ..nn import initializer as I
from .program import default_main_program, default_startup_program


def create_parameter(shape, dtype="float32", name=None, initializer=None,
                     is_bias=False, trainable=True):
    prog = default_main_program()
    block = prog.global_block()
    name = name or prog._unique_name("param")
    init = I._resolve(initializer, is_bias=is_bias)
    var = block.create_parameter(name, shape, dtype, initializer=init,
                                 trainable=trainable)
    sblock = default_startup_program().global_block()
    sblock.append_op("init_param", {"X": []}, {"Out": [name]},
                     {"initializer": init, "shape": list(shape), "dtype": dtype})
    return var


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None, activation=None,
       name=None):
    """fluid.layers.fc (fluid/layers/nn.py) — flatten + mul + bias + act."""
    in_features = 1
    for d in x.shape[num_flatten_dims:]:
        in_features *= d
    w = create_parameter([in_features, size], str(x.dtype), initializer=weight_attr)
    out = ops.mul(x, w, x_num_col_dims=num_flatten_dims)
    if bias_attr is not False:
        b = create_parameter([size], str(x.dtype), initializer=bias_attr, is_bias=True)
        out = ops.add(out, b)
    if activation:
        out = getattr(ops, activation)(out)
    return out


def conv2d(x, num_filters, filter_size, stride=1, padding=0, dilation=1, groups=1,
           weight_attr=None, bias_attr=None, activation=None, name=None):
    ks = filter_size if isinstance(filter_size, (list, tuple)) else (filter_size, filter_size)
    in_channels = x.shape[1]
    fan_in = in_channels // groups * ks[0] * ks[1]
    w = create_parameter(
        [num_filters, in_channels // groups, ks[0], ks[1]], str(x.dtype),
        initializer=weight_attr or I.KaimingUniform(fan_in=fan_in))
    out = ops.conv2d(x, w, None, stride=stride, padding=padding,
                     dilation=dilation, groups=groups)
    if bias_attr is not False:
        b = create_parameter([num_filters], str(x.dtype), initializer=bias_attr, is_bias=True)
        out = ops.add(out, ops.reshape(b, [1, num_filters, 1, 1]))
    if activation:
        out = getattr(ops, activation)(out)
    return out


def batch_norm(x, momentum=0.9, epsilon=1e-5, weight_attr=None, bias_attr=None,
               is_test=False, name=None):
    c = x.shape[1]
    scale = create_parameter([c], str(x.dtype), initializer=weight_attr or I.Constant(1.0))
    bias = create_parameter([c], str(x.dtype), initializer=bias_attr, is_bias=True)
    mean = create_parameter([c], str(x.dtype), initializer=I.Constant(0.0), trainable=False)
    var = create_parameter([c], str(x.dtype), initializer=I.Constant(1.0), trainable=False)
    mean.stop_gradient = True
    var.stop_gradient = True
    return ops.batch_norm(x, mean, var, scale, bias, training=not is_test,
                          momentum=momentum, epsilon=epsilon)


def embedding(x, size, padding_idx=None, weight_attr=None, name=None):
    w = create_parameter(list(size), "float32",
                         initializer=weight_attr or I.Normal(0.0, 1.0))
    return ops.embedding(x, w, padding_idx=padding_idx)


def layer_norm(x, begin_norm_axis=-1, epsilon=1e-5, weight_attr=None, bias_attr=None):
    if begin_norm_axis < 0:
        begin_norm_axis = len(x.shape) + begin_norm_axis
    shape = list(x.shape[begin_norm_axis:])
    scale = create_parameter(shape, str(x.dtype), initializer=weight_attr or I.Constant(1.0))
    bias = create_parameter(shape, str(x.dtype), initializer=bias_attr, is_bias=True)
    return ops.layer_norm(x, shape, scale, bias, epsilon)


def dropout(x, dropout_prob=0.5, is_test=False):
    return ops.dropout(x, p=dropout_prob, training=not is_test)


# -- control flow (operators/controlflow/, fluid/layers/control_flow.py) -----
from .control_flow import (  # noqa: E402,F401
    case,
    cond,
    scan,
    switch_case,
    while_loop,
)
