"""Static-graph Executor + Scope.

Reference parity: paddle/fluid/framework/executor.cc:180 (Executor::Run op
loop) + framework/scope.h:46 (Scope) + python/paddle/fluid/executor.py:474.

TPU-native design (SURVEY.md §7 step 2): instead of interpreting ops one by
one (the reference's hot loop, executor.cc:428), the whole block is traced
into ONE jax function and compiled by XLA per (program version, feed
shapes/dtypes) — the op loop collapses into a single fused HLO module, so
op-boundary overhead and intermediate materialization vanish. Gradient ops
("grad::<type>") are interpreted via jax.vjp of the forward kernel during
tracing — per-op grad kernels never need hand-writing. Persistable vars
(parameters, optimizer state, RNG-updated stats) are threaded in/out of the
compiled function and written back to the Scope after each run.
"""
from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..framework import random as _random
from ..framework.place import Place, _default_place
from ..framework.tensor import Tensor
from ..ops.registry import kernel
from .program import Program, default_main_program, default_startup_program


class Scope:
    """name → host/device array map (framework/scope.h:46)."""

    def __init__(self):
        self._vars: dict[str, jax.Array] = {}

    def set(self, name, value):
        self._vars[name] = jnp.asarray(value)

    def get(self, name):
        return self._vars[name]

    def has(self, name):
        return name in self._vars

    def var_names(self):
        return list(self._vars)

    def drop(self, name):
        self._vars.pop(name, None)

    def clear(self):
        self._vars.clear()


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def _trace_block(block, op_list, feed_names, fetch_names, persist_in, rng_ops):
    """Build the pure function for one block. Returns fn(feeds, persists, key)
    -> (fetches, updated_persists)."""

    def fn(feed_arrays, persist_arrays, base_key):
        env = {}
        env.update(dict(zip(feed_names, feed_arrays)))
        env.update(dict(zip(persist_in, persist_arrays)))
        written_persist = {}

        for op_index, op in enumerate(op_list):
            in_names = op.inputs.get("X", [])
            out_names = op.outputs.get("Out", [])
            attrs = {k: v for k, v in op.attrs.items() if not k.startswith("__")}

            if op.type.startswith("grad::"):
                fwd_type = op.type[len("grad::"):]
                fwd_fn = kernel(fwd_type)
                n_in = op.attrs["__n_fwd_in__"]
                fwd_in = [env[n] for n in in_names[:n_in]]
                out_grad_names = in_names[n_in:]
                f_attrs = dict(attrs)
                f_attrs.pop("__rng__", None)
                if op.attrs.get("__rng__"):
                    f_attrs["key"] = jax.random.fold_in(base_key, op.attrs["__rng_id__"])
                outs, vjp_fn = jax.vjp(partial(fwd_fn, **f_attrs), *fwd_in)
                outs_list = list(outs) if isinstance(outs, (tuple, list)) else [outs]
                cots = []
                for i, o in enumerate(outs_list):
                    gname = out_grad_names[i] if i < len(out_grad_names) else ""
                    if gname and gname in env:
                        cots.append(env[gname].astype(o.dtype))
                    elif jnp.issubdtype(o.dtype, np.floating):
                        cots.append(jnp.zeros(o.shape, o.dtype))
                    else:
                        cots.append(np.zeros(o.shape, dtype=jax.dtypes.float0))
                cot = tuple(cots) if len(cots) > 1 else cots[0]
                grads = vjp_fn(cot)
                results = []
                for g in grads:
                    results.append(None if (g is None or g.dtype == jax.dtypes.float0) else g)
            else:
                f_attrs = dict(attrs)
                if op.attrs.get("__rng__"):
                    f_attrs["key"] = jax.random.fold_in(base_key, op.attrs["__rng_id__"])
                fn_k = kernel(op.type)
                arrays = [env[n] for n in in_names]
                out = fn_k(*arrays, **f_attrs)
                results = list(out) if isinstance(out, (tuple, list)) else [out]

            for name, value in zip(out_names, results):
                if not name or value is None:
                    continue
                env[name] = value
                if block.has_var(name) and block.var(name).persistable:
                    written_persist[name] = value

        fetches = [env[n] for n in fetch_names]
        return fetches, written_persist

    return fn


class Executor:
    """fluid.Executor equivalent. Compiles blocks with jax.jit, caches by
    (program version, feed signature)."""

    def __init__(self, place: Place | None = None):
        self.place = place or _default_place()
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()

        fetch_names = [v if isinstance(v, str) else v.name for v in fetch_list]
        block = program.global_block()
        op_list = block.ops

        # init captured constants
        for cname, cval in getattr(program, "_constants", {}).items():
            if not scope.has(cname):
                scope.set(cname, cval)

        feed_names = sorted(feed.keys())
        feed_arrays = []
        for n in feed_names:
            v = feed[n]
            arr = v._array if isinstance(v, Tensor) else jnp.asarray(
                np.asarray(v, dtype=block.var(n).dtype if block.has_var(n) else None))
            feed_arrays.append(arr)

        # persistable inputs: every persistable var referenced by ops & present in scope
        referenced = set()
        for op in op_list:
            referenced.update(op.inputs.get("X", []))
            referenced.update(op.outputs.get("Out", []))
        persist_in = sorted(
            n for n in referenced
            if block.has_var(n) and block.var(n).persistable and scope.has(n)
            and n not in feed_names
        )

        # assign rng ids deterministically by op position
        rng_id = 0
        for op in op_list:
            if op.attrs.get("__rng__"):
                op.attrs["__rng_id__"] = rng_id
                rng_id += 1

        sig = (
            id(program), program._version, tuple(fetch_names), tuple(feed_names),
            tuple((tuple(a.shape), str(a.dtype)) for a in feed_arrays),
            tuple(persist_in),
        )
        entry = self._cache.get(sig)
        if entry is None:
            traced = _trace_block(block, list(op_list), feed_names, fetch_names,
                                  persist_in, rng_id)
            jitted = jax.jit(traced)
            entry = (jitted, persist_in)
            self._cache[sig] = entry
        jitted, persist_in = entry

        persist_arrays = [scope.get(n) for n in persist_in]
        base_key = _random.split_key()
        fetches, written = jitted(feed_arrays, persist_arrays, base_key)

        for name, value in written.items():
            scope.set(name, value)

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor._from_array(f) for f in fetches]

    # startup program: run initializer ops host-side (not jitted — once)
    def run_startup(self, startup_program=None, scope=None):
        startup_program = startup_program or default_startup_program()
        scope = scope or global_scope()
        block = startup_program.global_block()
        for op in block.ops:
            out_names = op.outputs.get("Out", [])
            if op.type == "init_param":
                init = op.attrs["initializer"]
                shape = op.attrs["shape"]
                dtype = op.attrs["dtype"]
                if not scope.has(out_names[0]):
                    scope.set(out_names[0], init(shape, dtype))
            else:
                fn = kernel(op.type)
                attrs = {k: v for k, v in op.attrs.items() if not k.startswith("__")}
                if op.attrs.get("__rng__"):
                    attrs["key"] = _random.split_key()
                arrays = [scope.get(n) for n in op.inputs.get("X", [])]
                out = fn(*arrays, **attrs)
                results = list(out) if isinstance(out, (tuple, list)) else [out]
                for n, v in zip(out_names, results):
                    if n:
                        scope.set(n, v)
