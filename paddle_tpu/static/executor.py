"""Static-graph Executor + Scope.

Reference parity: paddle/fluid/framework/executor.cc:180 (Executor::Run op
loop) + framework/scope.h:46 (Scope) + python/paddle/fluid/executor.py:474.

TPU-native design (SURVEY.md §7 step 2): instead of interpreting ops one by
one (the reference's hot loop, executor.cc:428), the whole block is traced
into ONE jax function and compiled by XLA per (program version, feed
shapes/dtypes) — the op loop collapses into a single fused HLO module, so
op-boundary overhead and intermediate materialization vanish. Gradient ops
("grad::<type>") are interpreted via jax.vjp of the forward kernel during
tracing — per-op grad kernels never need hand-writing. Persistable vars
(parameters, optimizer state, RNG-updated stats) are threaded in/out of the
compiled function and written back to the Scope after each run.

Control-flow ops (while/cond/scan, operators/controlflow/ in the reference)
consume nested blocks and lower to lax.while_loop / lax.cond / lax.scan:
sub-blocks are traced recursively into the same XLA module. Their grad ops
re-trace the sub-block as a pure closure over (explicit) inputs and
jax.vjp through it — lax.cond and lax.scan are reverse-differentiable by
construction; lax.while_loop is not (use scan for trainable loops).
"""
from __future__ import annotations

import contextlib
import threading
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..flags import flag, watch_flag
from ..framework import random as _random
from ..monitor import flight_recorder as _flight
from ..monitor import tracing as _tracing
from ..monitor.opprof import op_scope_name as _op_scope
from ..runtime.compiled import CompiledStore
from ..framework.place import Place, _default_place
from ..framework.tensor import Tensor
from ..ops.registry import kernel
from ..profiler import RecordEvent, bump_counter
from .program import Program, default_main_program, default_startup_program


class Scope:
    """name → host/device array map (framework/scope.h:46)."""

    def __init__(self):
        self._vars: dict[str, jax.Array] = {}

    def set(self, name, value):
        self._vars[name] = jnp.asarray(value)

    def get(self, name):
        return self._vars[name]

    def has(self, name):
        return name in self._vars

    def var_names(self):
        return list(self._vars)

    def drop(self, name):
        self._vars.pop(name, None)

    def clear(self):
        self._vars.clear()


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


_BLOCK_OPS = ("while", "cond", "scan")

# nullcontext is stateless — one shared instance keeps the steady-state
# dispatch path allocation-free
_NULL_CTX = contextlib.nullcontext()


def _walk_ops(program, block_idx, seen=None):
    """Yield (block, op) over a block and all nested sub-blocks."""
    from .control_flow import BLOCK_ATTR_KEYS

    if seen is None:
        seen = set()
    if block_idx in seen:
        return
    seen.add(block_idx)
    blk = program.blocks[block_idx]
    for op in blk.ops:
        yield blk, op
        for key, val in op.attrs.items():
            if key in BLOCK_ATTR_KEYS and isinstance(val, int):
                yield from _walk_ops(program, val, seen)


def _op_key(base_key, op, it=None):
    key = jax.random.fold_in(base_key, op.attrs["__rng_id__"])
    if it is not None:
        key = jax.random.fold_in(key, it)
    return key


def op_in_names(op):
    """Positional input names of an op.

    The reference's OpDesc keys io by named slots (framework.proto:42
    name-maps); this runtime canonically uses one "X" slot, but ops MAY
    declare named multi-slot inputs via the ``__in_slots__`` attr (an
    ordered slot list) — the kernel then receives the slots' vars
    concatenated in that order. Same for outputs via ``__out_slots__``.
    """
    slots = op.attrs.get("__in_slots__")
    if slots:
        return [n for s in slots for n in op.inputs.get(s, [])]
    return op.inputs.get("X", [])


def op_out_names(op):
    slots = op.attrs.get("__out_slots__")
    if slots:
        return [n for s in slots for n in op.outputs.get(s, [])]
    return op.outputs.get("Out", [])


class _LazyFetchList(list):
    """``run()`` fetch result: a list whose elements materialize to numpy
    on first access.

    ``return_numpy=True`` used to force a blocking ``np.asarray`` on every
    fetch every step; now the device->host sync happens at first element
    access, so a training loop that only inspects the loss every
    ``print_period`` steps dispatches the intervening steps without ever
    blocking on a transfer, and ``train_from_dataset`` overlaps batch
    N+1's H2D copy with step N's dispatch.
    """

    def _materialize(self, i):
        v = list.__getitem__(self, i)
        if not isinstance(v, np.ndarray):
            # the device->host sync the laziness deferred happens HERE —
            # span it so a trace shows exactly which access paid it
            with RecordEvent("executor::fetch_sync"):
                v = np.asarray(v)
            list.__setitem__(self, i, v)
        return v

    def _materialize_all(self):
        for i in range(len(self)):
            self._materialize(i)
        return self

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._materialize(j)
                    for j in range(*i.indices(len(self)))]
        return self._materialize(i)

    def __iter__(self):
        for i in range(len(self)):
            yield self._materialize(i)

    # C-level list paths that bypass __getitem__ must not leak raw device
    # arrays: materialize everything first, then defer to list
    def pop(self, i=-1):
        self._materialize_all()
        return list.pop(self, i)

    def copy(self):
        return list(self._materialize_all())

    def index(self, *a):
        return list.index(self._materialize_all(), *a)

    def remove(self, v):
        return list.remove(self._materialize_all(), v)

    def __reversed__(self):
        return list.__reversed__(self._materialize_all())

    def count(self, v):
        return list.count(self._materialize_all(), v)

    def __contains__(self, v):
        return list.__contains__(self._materialize_all(), v)

    def __eq__(self, other):
        return list.__eq__(self._materialize_all(), other)

    __hash__ = None

    def __add__(self, other):
        return list(self._materialize_all()) + list(other)

    def __radd__(self, other):
        return list(other) + list(self._materialize_all())

    def __mul__(self, n):
        return list.__mul__(self._materialize_all(), n)

    __rmul__ = __mul__

    def __repr__(self):
        return list.__repr__(self._materialize_all())

    def __reduce__(self):  # pickle ships numpy, never device handles
        return (list, (list(self._materialize_all()),))


# last FLAGS_persistent_compile_cache_dir value applied to jax.config
# (None = never applied), and the ambient jax cache settings saved before
# the first override so clearing the flag restores them all (a host app —
# or the test suite's conftest — may have configured its own cache)
_persistent_cache_applied = [None]
_ambient_cache_config = [None]

_CACHE_CONFIG_KEYS = (
    "jax_compilation_cache_dir",
    "jax_persistent_cache_min_compile_time_secs",
)


def _sync_persistent_cache():
    """Apply FLAGS_persistent_compile_cache_dir to jax's persistent
    compilation cache so repeated process starts skip XLA recompilation.
    Checked only on jit-entry misses — zero cost in the dispatch loop.
    An unset flag never touches ambient jax config."""
    d = flag("persistent_compile_cache_dir")
    if d == _persistent_cache_applied[0]:
        return
    if not d and _persistent_cache_applied[0] is None:
        _persistent_cache_applied[0] = d  # flag never set: hands off
        return
    try:
        if not _persistent_cache_applied[0]:
            _ambient_cache_config[0] = {
                k: getattr(jax.config, k) for k in _CACHE_CONFIG_KEYS}
        if d:
            jax.config.update("jax_compilation_cache_dir", d)
            # modest floor: low enough to capture every whole-block
            # executor compile, high enough that the process's tiny
            # per-op eager jits don't each pay a disk write (jax.config
            # is global — this affects ALL compiles in the process)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.1)
        else:  # flag cleared: hand the whole cache config back untouched
            for k, v in _ambient_cache_config[0].items():
                jax.config.update(k, v)
        # jax latches its cache handle at the first compile; re-pointing
        # the dir after any compile has happened needs an explicit reset
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception as e:  # older jax without the persistent-cache config
        import warnings

        warnings.warn(
            f"persistent_compile_cache_dir={d!r} could not be applied to "
            f"this jax ({type(e).__name__}: {e}); compiles will not be "
            "cached across process starts", RuntimeWarning, stacklevel=2)
    _persistent_cache_applied[0] = d


# set_flags must take effect immediately — clearing the flag restores the
# ambient jax cache config right away, not at the next jit-cache miss
watch_flag("persistent_compile_cache_dir", lambda _v: _sync_persistent_cache())


def _feed_shape(v):
    """Shape of a feed value without materializing it (Tensor, device
    array, ndarray, or nested list) — the memory-admission cache key's
    per-run component, so it must stay allocation-light."""
    a = getattr(v, "_array", None)
    if a is not None:
        return tuple(a.shape)
    s = getattr(v, "shape", None)
    if s is not None:
        return tuple(s)
    return tuple(np.shape(v))


def _plan_key(program):
    tok = getattr(program, "_identity_token", None)
    if tok is None:
        tok = id(program)
    return (tok, program._version)


class RunPlan:
    """Static dispatch plan for one (program identity, version), computed
    once and reused by every ``run()`` on that program state.

    Everything the executor used to re-derive per call by walking all ops
    — the referenced-persistable analysis, the statically-written
    persistable set (the donation candidates), rng-id assignment, the
    captured-constant list — lives here, so the steady-state hot path
    reduces to dict lookups plus the jitted call (TVM's split of one-time
    compilation from cheap repeated dispatch, arXiv 1802.04799). Any
    program mutation that matters goes through ``append_op``/
    ``_new_block``, which bump ``_version`` and so key a fresh plan;
    flipping a var's ``persistable`` flag without adding ops is the one
    mutation this cache cannot see.
    """

    __slots__ = ("key", "block", "op_list", "persist_candidates",
                 "written_names", "constants")

    def __init__(self, program):
        self.key = _plan_key(program)
        block = program.global_block()
        self.block = block
        self.op_list = list(block.ops)
        self.constants = list(getattr(program, "_constants", {}).items())

        # ONE walk over all ops (incl. nested control-flow blocks) collects
        # what three walks used to: referenced names, written persistables,
        # and rng-id assignment state.
        referenced = {}  # name -> owning block for persistable lookup
        written = set()
        next_id = 0
        rng_missing = []
        for blk, op in _walk_ops(program, 0):
            for names in list(op.inputs.values()) + list(op.outputs.values()):
                for n in names:
                    referenced.setdefault(n, blk)
            for n in op_out_names(op):
                if n and blk.has_var(n) and blk.var(n).persistable:
                    written.add(n)
            rid = op.attrs.get("__rng_id__")
            if rid is not None:
                next_id = max(next_id, rid + 1)
            elif op.attrs.get("__rng__"):
                rng_missing.append(op)
        # rng ids are assigned at build time (op_append.py) so grad ops
        # share their forward op's id; assign here only for ops that
        # predate that (e.g. hand-built/deserialized programs)
        for op in rng_missing:
            op.attrs["__rng_id__"] = next_id
            next_id += 1

        # persistable vars any op touches: the per-run persist_in is this
        # list filtered by scope membership — no op traversal at dispatch
        self.persist_candidates = tuple(sorted(
            n for n, blk in referenced.items()
            if blk.has_var(n) and blk.var(n).persistable
        ))
        self.written_names = frozenset(written)


class _BlockRunner:
    """Traces a program's ops into jax, recursively through sub-blocks."""

    def __init__(self, program):
        self.program = program
        self._pw_cache = {}

    # -- control-flow lowering ---------------------------------------------

    # salt folded into the key chain at every loop entry so nested loops
    # (scan-in-scan, dropout-under-cond-in-while) never reuse a key path
    _LOOP_SALT = 0x6F09

    def _persist_writes(self, blk):
        """Persistable vars written by the block's ops (recursing into
        nested control-flow blocks, whose writes propagate out the same
        way) — the scope-threading set for executor.cc:428-style scope
        semantics: these become extra block outputs so the update reaches
        the top-level Scope instead of dying with the sub-block."""
        if blk.idx in self._pw_cache:
            return self._pw_cache[blk.idx]
        names = []
        for op in blk.ops:
            if op.type in _BLOCK_OPS:
                for key in ("__body_block__", "__true_block__",
                            "__false_block__", "__cond_block__"):
                    bidx = op.attrs.get(key)
                    if bidx is not None:
                        names.extend(
                            self._persist_writes(self.program.blocks[bidx])
                        )
                continue
            for n in op_out_names(op):
                if n and blk.has_var(n) and blk.var(n).persistable:
                    names.append(n)
        out = sorted(set(names))
        self._pw_cache[blk.idx] = out
        return out

    def _record_pw(self, pw, values, env, written_persist):
        for n, v in zip(pw, values):
            env[n] = v
            if written_persist is not None:
                written_persist[n] = v

    def _run_while(self, op, env, base_key, outer_it=None,
                   written_persist=None):
        attrs = op.attrs
        n_loop = attrs["__n_loop__"]
        in_names = op.inputs["X"]
        loop_in = in_names[:n_loop]
        cond_blk = self.program.blocks[attrs["__cond_block__"]]
        body_blk = self.program.blocks[attrs["__body_block__"]]
        pw = self._persist_writes(body_blk)

        if outer_it is not None:
            base_key = jax.random.fold_in(base_key, outer_it)
        loop_key = jax.random.fold_in(base_key, self._LOOP_SALT)
        init = tuple(env[n] for n in loop_in)
        pw_init = tuple(env[n] for n in pw)

        def cond_f(carry_it):
            it, carry, pw_vals = carry_it
            sub = dict(env)
            sub.update(zip(pw, pw_vals))
            sub.update(zip(attrs["__cond_formals__"], carry))
            # None: a persistable write in a while's *condition* block has
            # no carry slot — it still fails loudly
            self.exec_ops(cond_blk.ops, sub,
                          jax.random.fold_in(loop_key, it), None,
                          block=cond_blk)
            pred = sub[attrs["__cond_out__"]]
            return jnp.reshape(pred, ()).astype(bool)

        def body_f(carry_it):
            it, carry, pw_vals = carry_it
            sub = dict(env)
            sub.update(zip(pw, pw_vals))
            sub.update(zip(attrs["__body_formals__"], carry))
            # per-iteration key: stochastic ops (sampling decoders) draw
            # fresh randomness each step, including in nested blocks
            self.exec_ops(body_blk.ops, sub,
                          jax.random.fold_in(loop_key, it), {},
                          block=body_blk)
            return (it + 1, tuple(sub[n] for n in attrs["__body_outs__"]),
                    tuple(sub[n] for n in pw))

        _, final, pw_final = lax.while_loop(
            cond_f, body_f, (jnp.asarray(0, jnp.int32), init, pw_init)
        )
        self._record_pw(pw, pw_final, env, written_persist)
        return list(final)

    def _run_cond(self, op, env, base_key, outer_it=None,
                  written_persist=None):
        attrs = op.attrs
        pred = env[op.inputs["X"][0]]
        true_blk = self.program.blocks[attrs["__true_block__"]]
        false_blk = self.program.blocks[attrs["__false_block__"]]
        # union: a branch that does not write a stat passes it through, so
        # both lax.cond branches emit the same structure
        pw = sorted(set(self._persist_writes(true_blk))
                    | set(self._persist_writes(false_blk)))

        def branch(blk, out_names):
            def f():
                sub = dict(env)
                # iteration context passes straight through a branch
                self.exec_ops(blk.ops, sub, base_key, {}, block=blk,
                              iter_idx=outer_it)
                return (tuple(sub[n] for n in out_names)
                        + tuple(sub[n] for n in pw))
            return f

        outs = lax.cond(
            jnp.reshape(pred, ()).astype(bool),
            branch(true_blk, attrs["__true_outs__"]),
            branch(false_blk, attrs["__false_outs__"]),
        )
        n_reg = len(outs) - len(pw)
        self._record_pw(pw, outs[n_reg:], env, written_persist)
        return list(outs[:n_reg])

    def _run_scan(self, op, env, base_key, outer_it=None,
                  written_persist=None):
        attrs = op.attrs
        n_c, n_s = attrs["__n_carry__"], attrs["__n_seq__"]
        in_names = op.inputs["X"]
        body_blk = self.program.blocks[attrs["__body_block__"]]
        pw = self._persist_writes(body_blk)

        if outer_it is not None:
            base_key = jax.random.fold_in(base_key, outer_it)
        loop_key = jax.random.fold_in(base_key, self._LOOP_SALT)
        init = tuple(env[n] for n in in_names[:n_c])
        seqs = tuple(env[n] for n in in_names[n_c:n_c + n_s])
        pw_init = tuple(env[n] for n in pw)

        def body_f(carry_it, xs):
            it, carry, pw_vals = carry_it
            sub = dict(env)
            sub.update(zip(pw, pw_vals))
            sub.update(zip(attrs["__carry_formals__"], carry))
            sub.update(zip(attrs["__seq_formals__"], xs or ()))
            self.exec_ops(body_blk.ops, sub,
                          jax.random.fold_in(loop_key, it), {},
                          block=body_blk)
            new_carry = tuple(sub[n] for n in attrs["__carry_outs__"])
            y = tuple(sub[n] for n in attrs["__y_outs__"])
            return (it + 1, new_carry, tuple(sub[n] for n in pw)), y

        (_, final, pw_final), ys = lax.scan(
            body_f, (jnp.asarray(0, jnp.int32), init, pw_init),
            seqs if seqs else None, length=attrs.get("__length__"),
        )
        self._record_pw(pw, pw_final, env, written_persist)
        return list(final) + list(ys)

    def _block_op_closure(self, op, env, base_key, outer_it=None):
        """Pure fn over the op's explicit inputs, for jax.vjp (grad ops)."""
        in_names = op.inputs["X"]

        def closure(*arrays):
            local = dict(env)
            local.update(zip(in_names, arrays))
            if op.type == "cond":
                outs = self._run_cond(op, local, base_key, outer_it)
            elif op.type == "scan":
                outs = self._run_scan(op, local, base_key, outer_it)
            else:  # while
                outs = self._run_while(op, local, base_key, outer_it)
            return tuple(outs)

        return closure

    # -- main interpreter ---------------------------------------------------

    def exec_ops(self, op_list, env, base_key, written_persist, block=None,
                 iter_idx=None):
        for op_index, op in enumerate(op_list):
            try:
                self._exec_one(op, env, base_key, written_persist, block,
                               iter_idx, op_index)
            except Exception as e:
                # PADDLE_ENFORCE behavior (platform/enforce.h): append the
                # failing op's context to the message, preserving the
                # original exception type; innermost op wins for nested
                # control-flow blocks
                marker = "[operator <"
                if e.args and isinstance(e.args[0], str) and marker in e.args[0]:
                    raise
                ctx = (
                    f"[operator < {op.type} > error] "
                    f"inputs={op.inputs.get('X', [])} "
                    f"outputs={op.outputs.get('Out', [])}"
                )
                head = e.args[0] if e.args else ""
                e.args = (f"{head}\n  {ctx}",) + tuple(e.args[1:])
                raise

    def _exec_one(self, op, env, base_key, written_persist, block=None,
                  iter_idx=None, op_index=None):
            in_names = op_in_names(op)
            out_names = op_out_names(op)
            attrs = {k: v for k, v in op.attrs.items() if not k.startswith("__")}

            if op.type in _BLOCK_OPS:
                results = getattr(self, f"_run_{op.type}")(
                    op, env, base_key, iter_idx,
                    written_persist=written_persist,
                )
            elif op.type.startswith("grad::"):
                fwd_type = op.type[len("grad::"):]
                n_in = op.attrs["__n_fwd_in__"]
                fwd_in = [env[n] for n in in_names[:n_in]]
                out_grad_names = in_names[n_in:]
                if fwd_type in _BLOCK_OPS:
                    if fwd_type == "while":
                        raise RuntimeError(
                            "while_loop is not reverse-differentiable on "
                            "XLA (unbounded trip count); build trainable "
                            "loops with paddle_tpu.static.nn.scan instead"
                        )
                    # the grad op carries the forward op's attrs (incl. the
                    # sub-block indices) and its input list is the forward
                    # X — enough to rebuild the forward closure
                    from .program import OpDesc

                    fwd_op = OpDesc(
                        fwd_type, {"X": in_names[:n_in]}, {"Out": []},
                        op.attrs,
                    )
                    fwd_fn = self._block_op_closure(
                        fwd_op, env, base_key, iter_idx
                    )
                    outs, vjp_fn = jax.vjp(fwd_fn, *fwd_in)
                else:
                    f_attrs = dict(attrs)
                    f_attrs.pop("__rng__", None)
                    if op.attrs.get("__rng__"):
                        f_attrs["key"] = _op_key(base_key, op, iter_idx)
                    fwd_fn = kernel(fwd_type)
                    outs, vjp_fn = jax.vjp(partial(fwd_fn, **f_attrs), *fwd_in)
                outs_list = list(outs) if isinstance(outs, (tuple, list)) else [outs]
                cots = []
                for i, o in enumerate(outs_list):
                    gname = out_grad_names[i] if i < len(out_grad_names) else ""
                    if gname and gname in env:
                        cots.append(env[gname].astype(o.dtype))
                    elif jnp.issubdtype(o.dtype, np.floating):
                        cots.append(jnp.zeros(o.shape, o.dtype))
                    else:
                        cots.append(np.zeros(o.shape, dtype=jax.dtypes.float0))
                if fwd_type in _BLOCK_OPS:
                    cot = tuple(cots)  # closure output is always a tuple
                else:
                    cot = tuple(cots) if len(cots) > 1 else cots[0]
                grads = vjp_fn(cot)
                results = []
                for g in grads:
                    results.append(
                        None if (g is None or g.dtype == jax.dtypes.float0) else g
                    )
            else:
                f_attrs = dict(attrs)
                if op.attrs.get("__rng__"):
                    f_attrs["key"] = _op_key(base_key, op, iter_idx)
                fn_k = kernel(op.type)
                arrays = [env[n] for n in in_names]
                # named_scope → HLO metadata, so device profiles attribute
                # fused kernels back to the framework op; the RecordEvent
                # costs only at trace time (once per compile) and gives the
                # reference-style per-op host table (profiler.h:126). The
                # scope carries the STAMPED identity op.type#<block>/<index>
                # (monitor/opprof grammar) so a trace row maps back to one
                # Program op, not just an op type — same-type ops in
                # different blocks stay distinguishable.
                scope_name = op.type if op_index is None else _op_scope(
                    op.type, block.idx if block is not None else 0, op_index)
                with RecordEvent(f"op::{op.type}"), \
                        jax.named_scope(scope_name):
                    out = fn_k(*arrays, **f_attrs)
                results = list(out) if isinstance(out, (tuple, list)) else [out]

            for name, value in zip(out_names, results):
                if not name or value is None:
                    continue
                env[name] = value
                if block is None:
                    continue
                if block.has_var(name) and block.var(name).persistable:
                    if written_persist is None:
                        # a context with no write-back path (a while's
                        # condition block): fail loudly instead of
                        # silently dropping the update
                        raise NotImplementedError(
                            f"op {op.type!r} writes persistable var "
                            f"{name!r} inside a while-condition block; "
                            "stateful updates belong in the loop body"
                        )
                    # sub-block writes reach the Scope via the enclosing
                    # cond/scan/while op's persist-thread outputs
                    # (_persist_writes), matching the reference executor's
                    # scope write-through (executor.cc:428)
                    written_persist[name] = value


def _trace_block(program, block, op_list, feed_names, fetch_names,
                 donate_names, hold_names):
    """Build the pure function for the top block. Returns
    fn(feeds, donated, held, key) -> (fetches, donated_out, extra_written).

    ``donated`` carries the persistable inputs the jit donates (the
    statically-written ones): their updated values ALWAYS come back,
    positionally, in ``donated_out``, so XLA aliases each update into its
    now-dead input buffer — parameters and optimizer state update in place
    instead of doubling HBM traffic each step. ``held`` carries read-only
    persistables (never donated, never returned). ``extra_written`` holds
    persistable writes outside the donated set (vars the run creates that
    were absent from the scope, or all writes when donation is off)."""
    runner = _BlockRunner(program)
    donate_set = frozenset(donate_names)

    def fn(feed_arrays, donated, held, base_key):
        env = {}
        env.update(zip(feed_names, feed_arrays))
        env.update(zip(donate_names, donated))
        env.update(zip(hold_names, held))
        written_persist = {}
        runner.exec_ops(op_list, env, base_key, written_persist, block=block)
        fetches = [env[n] for n in fetch_names]
        # env[n] is the var's final value whether or not the op that
        # writes it ran this trace (grad ops may emit None): a donated
        # input must always have an output aliased onto it
        donated_out = [env[n] for n in donate_names]
        extra = {n: v for n, v in written_persist.items()
                 if n not in donate_set}
        return fetches, donated_out, extra

    return fn


class Executor:
    """fluid.Executor equivalent. Two-level cache: a RunPlan per (program
    identity, version) holds the one-time op-walk analysis; compiled
    executables are keyed separately by (plan key, fetch/feed/persist
    signature) in the SHARED compiled-callable runtime
    (:mod:`paddle_tpu.runtime.compiled`) so re-feeding new shapes
    recompiles without re-planning — and so AOT compile, cost capture,
    LRU bounding, and the donation-safe demote-to-jit fallback follow
    the one policy every dispatch site shares."""

    def __init__(self, place: Place | None = None):
        self.place = place or _default_place()
        # the compiled-block cache: serving replica pools run one
        # Executor from N worker threads (Predictor.clone shares it so
        # compiles are shared) — the store's bookkeeping lock makes the
        # LRU pop-and-reinsert safe while dispatch stays unlocked
        # (concurrent device execution is the point of the pool)
        self._compiled = CompiledStore(
            "executor", cost_label="executor",
            hit_counter="executor::jit_cache_hit",
            miss_counter="executor::jit_cache_miss")
        self._plans = {}
        self._plan_cache_limit = 64  # RunPlan LRU bound
        self._cache_lock = threading.Lock()  # RunPlan bookkeeping

    # legacy cache surface (tests and notebooks poke these): a LIVE
    # mutable view of the entries (clear/del invalidate for real, so
    # the historical force-a-recompile workflow still works) and the
    # flag-governed LRU bound, both owned by the shared runtime store
    @property
    def _cache(self):
        return self._compiled.mapping()

    @property
    def _cache_limit(self):
        return self._compiled.capacity

    @_cache_limit.setter
    def _cache_limit(self, value):
        self._compiled.capacity = value

    def _plan_for(self, program):
        """RunPlan cache lookup (LRU, counter-instrumented). Returns
        (plan, "hit"|"miss") so run() can put the cache disposition in
        the flight-recorder event without re-deriving it."""
        key = _plan_key(program)
        with self._cache_lock:
            plan = self._plans.get(key)
            if plan is not None:
                self._plans[key] = self._plans.pop(key)  # refresh LRU order
                bump_counter("executor::plan_cache_hit")
                return plan, "hit"
        bump_counter("executor::plan_cache_miss")
        plan = RunPlan(program)
        with self._cache_lock:
            self._plans[key] = plan
            while len(self._plans) > self._plan_cache_limit:
                self._plans.pop(next(iter(self._plans)))
        return plan, "miss"

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()

        fetch_names = [v if isinstance(v, str) else v.name for v in fetch_list]
        feed_names = sorted(feed.keys())

        # IR verification gate (FLAGS_program_verify): a malformed program
        # fails HERE with the op index/type/var named — before any plan,
        # trace, or XLA lowering sees it. The verdict caches on the
        # Program per version (program.py Program.verify), so the steady
        # state pays one flag read + one dict lookup; failures also land
        # in the flight recorder as `program_verify` events. The gate
        # judges the program AS WRITTEN — the IR optimizer below runs
        # after it, so strict-mode findings (e.g. dead code) reject
        # before any rewrite could paper over them.
        verify_level = str(flag("program_verify")).strip().lower()
        if verify_level not in ("", "0", "off", "false", "no"):
            with RecordEvent("executor::program_verify"):
                program.verify(
                    feed_names=feed_names, fetch_list=fetch_names,
                    level="strict" if verify_level == "strict" else "on")

        # IR optimizer gate (FLAGS_ir_opt_level): rewrite the program onto
        # the fused registry kernels (+ DCE, + remat at level 2) BEFORE the
        # memplan gate and lowering, so admission and compilation see what
        # will actually run. optimize_program clones (the caller's program
        # is never mutated), caches per program version, and hands back
        # the ORIGINAL object when nothing was rewritten — so the
        # RunPlan/compile caches below key on a stable identity either way.
        try:
            ir_level = int(str(flag("ir_opt_level")).strip() or "0")
        except ValueError:
            ir_level = 0
        if ir_level > 0:
            from ..analysis import optimizer as _iropt

            with RecordEvent("executor::ir_opt"):
                program = _iropt.optimize_program(
                    program, feed_names, fetch_names, level=ir_level,
                    feed_shapes={n: _feed_shape(feed[n])
                                 for n in feed_names}).program

        # Static peak-HBM admission (FLAGS_memory_budget_check): plan the
        # program's liveness footprint and compare it against the device
        # HBM budget BEFORE any plan/lower/compile — an over-budget
        # program (or a liveness-unsafe donation) fails here with the
        # high-water op and top tensors named instead of OOMing
        # mid-compile. Verdicts cache per program version (the verifier-
        # cache discipline), so steady state pays feed-shape tuples plus
        # one dict lookup (bench.py executor_dispatch.memplan, <1%).
        mem_plan = None
        budget_level = str(flag("memory_budget_check")).strip().lower()
        if budget_level not in ("", "0", "off", "false", "no"):
            from ..analysis import memory as _memory

            feed_shapes = {n: _feed_shape(feed[n]) for n in feed_names}
            with RecordEvent("executor::memory_plan"):
                mem_plan = _memory.check_memory_budget(
                    program, feed_names, fetch_names,
                    feed_shapes=feed_shapes,
                    level="strict" if budget_level == "strict"
                    else "warn")

        with RecordEvent("executor::plan"):
            plan, plan_disposition = self._plan_for(program)
            block = plan.block

            # init captured constants
            for cname, cval in plan.constants:
                if not scope.has(cname):
                    scope.set(cname, cval)

        with RecordEvent("executor::feed"):  # H2D feed staging
            feed_arrays = []
            for n in feed_names:
                v = feed[n]
                if isinstance(v, Tensor):
                    arr = v._array
                elif isinstance(v, jax.Array):
                    arr = v  # device-resident feed (prefetch path): as is
                else:
                    arr = jnp.asarray(np.asarray(
                        v,
                        dtype=block.var(n).dtype if block.has_var(n) else None,
                    ))
                feed_arrays.append(arr)

        with RecordEvent("executor::dispatch_prep"):
            # persistable inputs: the plan's candidates filtered by scope
            # membership — dict lookups only, no op traversal
            persist_in = tuple(
                n for n in plan.persist_candidates
                if n not in feed and scope.has(n)
            )

            # the donation flag is part of the key: toggling it at runtime
            # (the documented debugging workflow) must not silently reuse
            # an entry compiled with the other donation mode
            donate_enabled = bool(flag("executor_buffer_donation"))
            sig = (
                plan.key, tuple(fetch_names), tuple(feed_names),
                tuple((tuple(a.shape), str(a.dtype)) for a in feed_arrays),
                persist_in, donate_enabled,
            )
        def _build():
            _sync_persistent_cache()
            # donation POLICY (shared flag semantics, one compile key):
            # donate the persistables the program statically writes
            # (params, optimizer state) — XLA aliases each update into
            # the input buffer. Read-only persistables are held
            # undonated.
            if donate_enabled:
                dn = tuple(
                    n for n in persist_in if n in plan.written_names)
            else:
                dn = ()
            hn = tuple(n for n in persist_in if n not in dn)
            traced = _trace_block(program, block, plan.op_list,
                                  feed_names, fetch_names, dn, hn)
            jitted = jax.jit(
                traced, donate_argnums=(1,) if dn else ())
            return jitted, (dn, hn)

        # the shared runtime owns the rest: LRU bookkeeping (thread-safe
        # for replica pools), the double-checked one-time AOT compile with
        # cost capture, and the donation-safe demote-to-jit fallback
        entry, jit_disposition = self._compiled.get_or_build(sig, _build)
        donate_names, hold_names = entry.meta
        first_run = jit_disposition == "miss"

        # flight-recorder breadcrumb: which program ran, and whether the
        # caches served it — a post-mortem can see a retrace storm (jit
        # misses racing run counts) or an unexpected re-plan at a glance.
        # cache_key is the shared runtime identity the CostRecord ledger
        # and /tracez cite for the same dispatch.
        program_id = f"{plan.key[0]}@v{plan.key[1]}"
        _flight.record_event(
            "executor_run_begin", program=program_id,
            plan_cache=plan_disposition, jit_cache=jit_disposition,
            cache_key=entry.cache_key,
            feeds=len(feed_names), fetches=len(fetch_names),
            donated=len(donate_names))
        # a serving dispatch (or any traced caller) sees compile-vs-
        # execute without threading a handle down here: the cache
        # disposition lands on whatever span is current (no-op outside
        # a trace — one contextvar read)
        _tracing.annotate(
            program=program_id, plan_cache=plan_disposition,
            jit_cache=jit_disposition, cache_key=entry.cache_key)

        donated = [scope.get(n) for n in donate_names]
        held = [scope.get(n) for n in hold_names]
        base_key = _random.split_key()
        # first run per signature traces + compiles (the per-op events fire
        # inside the trace); later runs are pure dispatch. The nested
        # jit_compile span isolates the XLA trace+compile cost from the
        # steady-state device step in the exported timeline.
        phase = "executor::compile_and_run" if first_run else "executor::run"
        # the dispatch span is steady-state ONLY: on first_run the same
        # interval is the jit_compile span, and letting dispatch wrap the
        # compile would skew its max/ave aggregates by orders of magnitude
        compile_span = (RecordEvent("executor::jit_compile") if first_run
                        else _NULL_CTX)
        dispatch_span = (_NULL_CTX if first_run
                         else RecordEvent("executor::dispatch"))
        try:
            with RecordEvent(phase), compile_span, dispatch_span:
                fetches, donated_out, extra = self._compiled.dispatch(
                    entry, feed_arrays, donated, held, base_key,
                    donated=donated,
                    capture_meta={"program": program_id})
        except Exception as e:
            _flight.record_event(
                "executor_run_error", program=program_id,
                error=f"{type(e).__name__}: {e}"[:500])
            if donate_names:
                # the donated scope buffers may already be consumed and
                # cannot be restored; say so instead of letting the next
                # scope.get surface a bare 'Array has been deleted'
                note = (
                    f"run() failed after donating {len(donate_names)} "
                    "persistable buffer(s); their scope state may be "
                    "invalidated. Re-run startup/state loading before "
                    "continuing, or set FLAGS_executor_buffer_donation=0 "
                    "to debug with donation off."
                )
                head = e.args[0] if e.args else ""
                e.args = (f"{head}\n  {note}",) + tuple(e.args[1:])
            raise
        # (the executed-work ledger bump and the trace's flops/cache_key
        # annotation happened inside the shared runtime's dispatch)
        if first_run and mem_plan is not None:
            # accuracy closure: the AOT compile just captured XLA's own
            # memory_analysis — ledger predicted-vs-actual so the planner
            # is certified against what the compiler actually built
            # (plan_accuracy on the CostRecord, /costz, /statz gauge)
            from ..analysis import memory as _memory

            _memory.note_actual(entry.record, mem_plan)
        if donate_names:
            bump_counter("executor::donated_buffers", len(donate_names))
            # a fetch may share its buffer with a value the scope holds and
            # donates NEXT run — directly (fetching a written persistable)
            # or via XLA output aliasing (fetching a no-op transform of
            # one). Sever every alias so fetch results survive and host
            # views of them stay stable; training fetches are small
            # (losses/metrics), so the copies are noise next to the step.
            fetches = [jnp.copy(f) for f in fetches]

        nan_scan = flag("check_nan_inf")
        if nan_scan and not donate_names:
            # nothing was donated: scan BEFORE writeback so a NaN abort
            # preserves the pre-step scope state for inspection (the
            # historical debugging behavior; with donation the pre-step
            # buffers are already dead, so writeback must come first)
            self._scan_nan_inf(program, fetch_names, fetches, extra)

        with RecordEvent("executor::writeback"):
            # Scope ownership transfer: the donated inputs are dead after
            # the call (XLA reused their buffers); the scope now owns the
            # returned arrays, so no stale reference survives for a later
            # read.
            for name, value in zip(donate_names, donated_out):
                scope.set(name, value)
            for name, value in extra.items():
                scope.set(name, value)

        if nan_scan and donate_names:
            # FLAGS_check_nan_inf: post-run scan of everything the block
            # produced, naming the first non-finite variable (the
            # variable-level analog of nan_inf_utils_detail.cc's per-op
            # output scan; the op is identified by its output var name)
            written_all = dict(zip(donate_names, donated_out))
            written_all.update(extra)
            self._scan_nan_inf(program, fetch_names, fetches, written_all)

        _flight.record_event("executor_run_end", program=program_id, ok=True)
        _flight.notify_progress("executor_run")

        if return_numpy:
            # lazy: the device->host sync happens at first element access,
            # so the caller can enqueue the next step first
            return _LazyFetchList(fetches)
        return [Tensor._from_array(f) for f in fetches]

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Drive the compiled step over a Dataset's batch stream
        (fluid/executor.py:1597 train_from_dataset).

        Where the reference hands the whole Dataset to C++ trainer threads
        (MultiTrainer), here the Dataset's parse workers stream fixed-shape
        batches (io/feed.py) and each batch runs through the jitted
        whole-block step — one compile, N dispatches. Batches are
        device-prefetched (DatasetBase._iter_device_batches) so batch
        N+1's H2D transfer overlaps step N's dispatch, and the lazy
        fetches only sync at print_period. Returns the number of batches
        consumed.
        """
        if dataset is None:
            raise ValueError("train_from_dataset needs a dataset")
        program = program or default_main_program()
        scope = scope or global_scope()
        if thread:
            dataset.set_thread(thread)
        fetch_list = fetch_list or []
        fetch_names = [v if isinstance(v, str) else v.name
                       for v in fetch_list]
        labels = fetch_info or fetch_names
        feed_names = dataset._feed_names()
        n = 0
        batches = (dataset._iter_device_batches()
                   if hasattr(dataset, "_iter_device_batches")
                   else dataset._iter_batches())
        for batch in batches:
            feed = dict(zip(feed_names, batch))
            fetches = self.run(program, feed=feed, fetch_list=fetch_list,
                               scope=scope)
            n += 1
            if fetch_list and (debug or n % print_period == 0):
                msg = ", ".join(
                    f"{lbl}={np.asarray(v).ravel()[:4]}"
                    for lbl, v in zip(labels, fetches)
                )
                print(f"[train_from_dataset] batch {n}: {msg}")
        return n

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Inference twin of train_from_dataset (fluid/executor.py:1658);
        identical driving loop — the program simply contains no optimizer
        ops."""
        return self.train_from_dataset(
            program, dataset, scope, thread, debug, fetch_list, fetch_info,
            print_period,
        )

    @staticmethod
    def _scan_nan_inf(program, fetch_names, fetches, written):
        from ..errors import FatalError, op_error_context

        def first_bad(named):
            for name, arr in named:
                a = np.asarray(arr)
                if np.issubdtype(a.dtype, np.floating) and not np.all(
                    np.isfinite(a)
                ):
                    return name
            return None

        bad = first_bad(
            list(zip(fetch_names, fetches)) + list(written.items())
        )
        if bad is None:
            return
        # FLAGS_check_nan_inf_action decides what detection does (raise /
        # warn-and-continue / dump-then-raise) — shared policy with the
        # checkify train-step path, see flight_recorder.nan_event_action
        if _flight.nan_event_action(
                f"var:{bad}",
                f"variable {bad!r} contains NaN/Inf after the block ran",
        ) is None:
            return  # warn: the run continues
        producer = None
        for _, op in _walk_ops(program, 0):
            if bad in [n for ns in op.outputs.values() for n in ns]:
                producer = op
                break
        ctx = op_error_context(producer) if producer is not None else None
        raise FatalError(
            f"check_nan_inf: variable {bad!r} contains NaN/Inf after the "
            f"block ran",
            op_context=ctx,
        )

    # startup program: run initializer ops host-side (not jitted — once)
    def run_startup(self, startup_program=None, scope=None):
        startup_program = startup_program or default_startup_program()
        scope = scope or global_scope()
        block = startup_program.global_block()
        for op in block.ops:
            out_names = op.outputs.get("Out", [])
            if op.type == "init_param":
                init = op.attrs["initializer"]
                shape = op.attrs["shape"]
                dtype = op.attrs["dtype"]
                if not scope.has(out_names[0]):
                    scope.set(out_names[0], init(shape, dtype))
            else:
                fn = kernel(op.type)
                attrs = {k: v for k, v in op.attrs.items() if not k.startswith("__")}
                if op.attrs.get("__rng__"):
                    attrs["key"] = _random.split_key()
                arrays = [scope.get(n) for n in op.inputs.get("X", [])]
                out = fn(*arrays, **attrs)
                results = list(out) if isinstance(out, (tuple, list)) else [out]
                for n, v in zip(out_names, results):
                    if n:
                        scope.set(n, v)
