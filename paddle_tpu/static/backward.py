"""Static autodiff: append_backward.

Reference parity: python/paddle/fluid/backward.py:1215 (append_backward) and
:862 (_append_backward_ops_). Walks the op list in reverse, appending one
"grad::<fwd_type>" op per forward op; the executor evaluates it with
jax.vjp of the forward kernel — replacing the reference's per-op C++
GradOpMaker registry (framework/grad_op_desc_maker.h) with derivation that
is exact by construction. Multi-consumer gradient accumulation inserts
sum_n ops exactly like fluid/backward.py's _addup_repetitive_outputs_.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .program import default_main_program


def _is_float_var(block, name):
    try:
        v = block.var(name)
    except KeyError:
        return False
    return jnp.issubdtype(v.dtype, np.floating)


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Appends grad ops for `loss`; returns [(param, grad_var)] pairs."""
    prog = default_main_program()
    block = loss.block if hasattr(loss, "block") else prog.global_block()
    ops = block.ops
    no_grad_set = set(no_grad_set or [])

    # forward pass: which vars require grad. `tainted` tracks values whose
    # gradient path runs through a while op (lax.while_loop has no VJP):
    # any loss depending on a tainted value must fail loudly, or the
    # while-path contribution would be silently dropped from the total.
    requires = set()
    tainted = set()
    for v in block.vars.values():
        if not v.stop_gradient and _is_float_var(block, v.name):
            requires.add(v.name)
    for op in ops:
        all_ins = [n for names in op.inputs.values() for n in names]
        all_outs = [n for names in op.outputs.values() for n in names]
        if op.type == "while":
            if any(n in requires or n in tainted for n in all_ins):
                tainted.update(all_outs)
            continue  # gradient barrier: lax.while_loop has no reverse mode
        if any(n in tainted for n in all_ins):
            tainted.update(all_outs)
        ins = op.inputs.get("X", [])
        outs = op.outputs.get("Out", [])
        if any(n in requires for n in ins):
            for n in outs:
                if _is_float_var(block, n) and n not in no_grad_set:
                    requires.add(n)

    if loss.name in tainted:
        raise RuntimeError(
            f"loss {loss.name!r} depends on the output of a while op, which "
            "is not reverse-differentiable in static autodiff "
            "(lax.while_loop has no VJP rule). Pass max_iters=N to "
            "while_loop for the differentiable masked-scan lowering, "
            "rewrite the loop with static.nn.scan, or detach the while "
            "outputs from the loss."
        )
    if loss.name not in requires:
        raise RuntimeError(
            f"loss {loss.name!r} does not depend on any trainable variable")

    # grad map: var name -> current grad var name
    grad_map: dict[str, str] = {}
    loss_grad = block.create_var(name=loss.name + "@GRAD", shape=loss.shape,
                                 dtype=str(loss.dtype))
    block.append_op("fill_any_like", {"X": [loss.name]}, {"Out": [loss_grad.name]},
                    {"value": 1.0})
    grad_map[loss.name] = loss_grad.name

    n_fwd_ops = len(ops)
    for i in range(n_fwd_ops - 1, -1, -1):
        op = ops[i]
        if op.type == "while":
            continue  # loss does not flow through it (taint-checked above)
        in_names = op.inputs.get("X", [])
        out_names = op.outputs.get("Out", [])
        out_grads = [grad_map.get(n) for n in out_names]
        if all(g is None for g in out_grads):
            continue
        if not any(n in requires for n in in_names):
            continue

        grad_in = list(in_names) + [g or "" for g in out_grads]
        grad_out = []
        accum_jobs = []  # (var, existing_grad, new_grad)
        for n in in_names:
            if n not in requires or n in no_grad_set:
                grad_out.append("")
                continue
            base = n + "@GRAD"
            if n in grad_map:
                fresh = prog._unique_name(base)
                accum_jobs.append((n, grad_map[n], fresh))
                gname = fresh
            else:
                gname = base if not block.has_var(base) else prog._unique_name(base)
                grad_map[n] = gname
            if not block.has_var(gname):
                src = block.var(n)
                gv = block.create_var(name=gname, shape=src.shape, dtype=str(src.dtype))
                gv.stop_gradient = True
            grad_out.append(gname)

        attrs = dict(op.attrs)
        attrs["__n_fwd_in__"] = len(in_names)
        # grad ops whose out_grad inputs include "" placeholders are resolved
        # by the executor (zero cotangent)
        block.append_op("grad::" + op.type, {"X": [g for g in grad_in if g]},
                        {"Out": grad_out}, attrs)
        # fix input list: executor slices by __n_fwd_in__, so keep placeholders
        block.ops[-1].inputs["X"] = grad_in

        for n, old, fresh in accum_jobs:
            acc = prog._unique_name(n + "@GRAD@ACC")
            src = block.var(n)
            av = block.create_var(name=acc, shape=src.shape, dtype=str(src.dtype))
            av.stop_gradient = True
            block.append_op("sum_n", {"X": [old, fresh]}, {"Out": [acc]}, {})
            grad_map[n] = acc

    params = parameter_list or [v.name for v in block.vars.values()
                                if getattr(v, "is_parameter", False)]
    result = []
    for p in params:
        pname = p if isinstance(p, str) else p.name
        if pname in grad_map:
            result.append((block.var(pname), block.var(grad_map[pname])))
    return result


def gradients(targets, inputs, target_gradients=None):
    """paddle.static.gradients (fluid/backward.py:1665 calc_gradient)."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    pairs = append_backward(targets[0], parameter_list=[v.name for v in inputs])
    by_name = {p.name: g for p, g in pairs}
    return [by_name.get(v.name) for v in inputs]
