"""Structured errors (PADDLE_ENFORCE equivalent).

Reference parity: paddle/fluid/platform/enforce.h (PADDLE_ENFORCE_* +
EnforceNotMet), platform/errors.cc and error_codes.proto (the canonical
error-code taxonomy), pybind/exception.cc (mapping to Python types).

Each error carries optional op context (type + io names) the way
EnforceNotMet carries the op callstack; verbosity follows
FLAGS_call_stack_level (enforce.h behavior).
"""
from __future__ import annotations

import traceback

__all__ = [
    "EnforceNotMet",
    "InvalidArgumentError",
    "NotFoundError",
    "OutOfRangeError",
    "AlreadyExistsError",
    "ResourceExhaustedError",
    "PreconditionNotMetError",
    "PermissionDeniedError",
    "ExecutionTimeoutError",
    "UnimplementedError",
    "UnavailableError",
    "FatalError",
    "ExternalError",
    "enforce",
    "op_error_context",
]


class EnforceNotMet(RuntimeError):
    """Base structured error (enforce.h EnforceNotMet).

    ``code`` mirrors error_codes.proto; ``op_context`` is a dict with the
    failing op's type and io names when raised from an executor path.
    """

    code = "UNKNOWN"

    def __init__(self, message, op_context=None):
        self.raw_message = str(message)
        self.op_context = op_context
        super().__init__(self._format())

    def _format(self):
        from .flags import flag

        try:
            level = int(flag("call_stack_level"))
        except Exception:
            level = 1
        parts = [f"[{self.code}] {self.raw_message}"]
        if level >= 1 and self.op_context:
            ctx = self.op_context
            io = ""
            if ctx.get("inputs") is not None:
                io = (f" inputs={list(ctx['inputs'])}"
                      f" outputs={list(ctx.get('outputs', []))}")
            parts.append(
                f"  [operator < {ctx.get('op_type', '?')} > error]{io}"
            )
        if level >= 2:
            stack = "".join(traceback.format_stack()[:-3])
            parts.append("  [python call stack]\n" + stack)
        return "\n".join(parts)


class InvalidArgumentError(EnforceNotMet):
    code = "INVALID_ARGUMENT"


class NotFoundError(EnforceNotMet):
    code = "NOT_FOUND"


class OutOfRangeError(EnforceNotMet):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(EnforceNotMet):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(EnforceNotMet):
    code = "RESOURCE_EXHAUSTED"


class PreconditionNotMetError(EnforceNotMet):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(EnforceNotMet):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(EnforceNotMet):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(EnforceNotMet):
    code = "UNIMPLEMENTED"


class UnavailableError(EnforceNotMet):
    code = "UNAVAILABLE"


class FatalError(EnforceNotMet):
    code = "FATAL"


class ExternalError(EnforceNotMet):
    code = "EXTERNAL"


def enforce(condition, message, etype=InvalidArgumentError, op_context=None):
    """PADDLE_ENFORCE: raise ``etype`` when ``condition`` is falsy."""
    if not condition:
        raise etype(message, op_context=op_context)
    return True


def op_error_context(op):
    """Build the op-context dict from a static-graph OpDesc."""
    return {
        "op_type": getattr(op, "type", "?"),
        "inputs": [n for ns in getattr(op, "inputs", {}).values() for n in ns],
        "outputs": [
            n for ns in getattr(op, "outputs", {}).values() for n in ns
        ],
    }
