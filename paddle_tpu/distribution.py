"""Probability distributions.

Reference parity: python/paddle/fluid/layers/distributions.py (Uniform,
Normal, Categorical, MultivariateNormalDiag — sample/entropy/log_prob/kl)
— rebuilt over the eager Tensor API so sampling threads through the global
PRNG (framework/random.py) and everything is differentiable where the
math allows (reparameterized Normal/Uniform samples).
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from . import ops
from .framework import random as _random
from .framework.tensor import Tensor, to_tensor

__all__ = [
    "Distribution", "Uniform", "Normal", "Bernoulli", "Categorical",
    "MultivariateNormalDiag", "kl_divergence",
]


def _t(x):
    if isinstance(x, Tensor):
        return x
    return to_tensor(np.asarray(x, dtype="float32"))


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        return ops.exp(self.log_prob(value))

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U(low, high); reparameterized sampling."""

    def __init__(self, low, high):
        self.low = _t(low)
        self.high = _t(high)

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self.low.shape)
        u = jax.random.uniform(_random.split_key(), shape, jnp.float32)
        return Tensor._from_array(
            self.low._array + u * (self.high._array - self.low._array)
        )

    def log_prob(self, value):
        value = _t(value)
        inside = ops.logical_and(
            ops.greater_equal(value, self.low), ops.less_than(value, self.high)
        )
        lp = -ops.log(ops.subtract(self.high, self.low))
        neg_inf = ops.full_like(lp, -np.inf)
        return ops.where(inside, lp, neg_inf)

    def entropy(self):
        return ops.log(ops.subtract(self.high, self.low))

    def kl_divergence(self, other):
        if not isinstance(other, Uniform):
            raise TypeError("kl(Uniform || non-Uniform) unsupported")
        return ops.log(ops.divide(
            ops.subtract(other.high, other.low),
            ops.subtract(self.high, self.low),
        ))


class Normal(Distribution):
    """N(loc, scale); reparameterized sampling."""

    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self.loc.shape)
        eps = jax.random.normal(_random.split_key(), shape, jnp.float32)
        return Tensor._from_array(self.loc._array + eps * self.scale._array)

    def log_prob(self, value):
        value = _t(value)
        var = ops.square(self.scale)
        return ops.subtract(
            ops.scale(ops.divide(ops.square(ops.subtract(value, self.loc)),
                                 var), -0.5),
            ops.add(ops.log(self.scale),
                    ops.full_like(self.scale, 0.5 * math.log(2 * math.pi))),
        )

    def entropy(self):
        return ops.add(ops.log(self.scale),
                       ops.full_like(self.scale,
                                     0.5 * (1.0 + math.log(2 * math.pi))))

    def kl_divergence(self, other):
        if not isinstance(other, Normal):
            raise TypeError("kl(Normal || non-Normal) unsupported")
        var_ratio = ops.square(ops.divide(self.scale, other.scale))
        t1 = ops.square(ops.divide(ops.subtract(self.loc, other.loc),
                                   other.scale))
        return ops.scale(
            ops.subtract(ops.add(var_ratio, t1),
                         ops.add(ops.log(var_ratio),
                                 ops.full_like(var_ratio, 1.0))),
            0.5,
        )


class Bernoulli(Distribution):
    def __init__(self, probs):
        self.p = _t(probs)

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self.p.shape)
        u = jax.random.uniform(_random.split_key(), shape, jnp.float32)
        return Tensor._from_array((u < self.p._array).astype(jnp.float32))

    def log_prob(self, value):
        value = _t(value)
        eps = 1e-8
        return ops.add(
            ops.multiply(value, ops.log(ops.clip(self.p, eps, 1.0))),
            ops.multiply(
                ops.subtract(ops.full_like(value, 1.0), value),
                ops.log(ops.clip(ops.subtract(ops.full_like(self.p, 1.0),
                                              self.p), eps, 1.0)),
            ),
        )

    def entropy(self):
        eps = 1e-8
        q = ops.subtract(ops.full_like(self.p, 1.0), self.p)
        return ops.scale(
            ops.add(ops.multiply(self.p, ops.log(ops.clip(self.p, eps, 1.0))),
                    ops.multiply(q, ops.log(ops.clip(q, eps, 1.0)))),
            -1.0,
        )


class Categorical(Distribution):
    def __init__(self, logits):
        self.logits = _t(logits)

    def _log_p(self):
        return ops.log_softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        out = jax.random.categorical(
            _random.split_key(), self.logits._array, axis=-1,
            shape=tuple(shape) + tuple(self.logits.shape[:-1]),
        )
        return Tensor._from_array(out)

    def log_prob(self, value):
        value = _t(value)
        lp = self._log_p()
        idx = ops.cast(value, "int64")
        return ops.take_along_axis(
            lp, ops.unsqueeze(idx, -1), axis=-1
        ).squeeze(-1)

    def entropy(self):
        lp = self._log_p()
        return ops.scale(ops.sum(ops.multiply(ops.exp(lp), lp), axis=-1), -1.0)

    def kl_divergence(self, other):
        if not isinstance(other, Categorical):
            raise TypeError("kl(Categorical || non-Categorical) unsupported")
        lp = self._log_p()
        lq = other._log_p()
        return ops.sum(ops.multiply(ops.exp(lp), ops.subtract(lp, lq)),
                       axis=-1)


class MultivariateNormalDiag(Distribution):
    """N(loc, diag(scale^2)) (distributions.py MultivariateNormalDiag)."""

    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)  # diagonal stds [.., D]

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self.loc.shape)
        eps = jax.random.normal(_random.split_key(), shape, jnp.float32)
        return Tensor._from_array(self.loc._array + eps * self.scale._array)

    def log_prob(self, value):
        value = _t(value)
        d = self.loc.shape[-1]
        z = ops.divide(ops.subtract(value, self.loc), self.scale)
        return ops.subtract(
            ops.scale(ops.sum(ops.square(z), axis=-1), -0.5),
            ops.add(ops.sum(ops.log(self.scale), axis=-1),
                    ops.full([], 0.5 * d * math.log(2 * math.pi))),
        )

    def entropy(self):
        d = self.loc.shape[-1]
        return ops.add(
            ops.sum(ops.log(self.scale), axis=-1),
            ops.full([], 0.5 * d * (1.0 + math.log(2 * math.pi))),
        )

    def kl_divergence(self, other):
        if not isinstance(other, MultivariateNormalDiag):
            raise TypeError("kl between different families unsupported")
        var_ratio = ops.square(ops.divide(self.scale, other.scale))
        t1 = ops.square(ops.divide(ops.subtract(self.loc, other.loc),
                                   other.scale))
        return ops.scale(
            ops.sum(
                ops.subtract(ops.add(var_ratio, t1),
                             ops.add(ops.log(var_ratio),
                                     ops.full_like(var_ratio, 1.0))),
                axis=-1,
            ),
            0.5,
        )


def kl_divergence(p, q):
    return p.kl_divergence(q)
