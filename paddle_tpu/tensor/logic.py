"""paddle_tpu.tensor.logic — the 2.0 tensor-API split.

Reference parity: python/paddle/tensor/logic.py (the 2.0 namespace
rework present in the snapshot). Thin categorized re-exports of the
mode-aware ops surface; implementations live in paddle_tpu.ops.
"""

from ..ops import equal  # noqa: F401
from ..ops import greater_equal  # noqa: F401
from ..ops import greater_than  # noqa: F401
from ..ops import less_equal  # noqa: F401
from ..ops import less_than  # noqa: F401
from ..ops import logical_and  # noqa: F401
from ..ops import logical_not  # noqa: F401
from ..ops import logical_or  # noqa: F401
from ..ops import logical_xor  # noqa: F401
from ..ops import not_equal  # noqa: F401
from ..ops import allclose  # noqa: F401
from ..ops import equal_all  # noqa: F401
from ..ops import isclose  # noqa: F401
from ..ops import isnan  # noqa: F401
from ..ops import isinf  # noqa: F401
from ..ops import isfinite  # noqa: F401
from ..ops import is_empty  # noqa: F401
