"""paddle_tpu.tensor.creation — the 2.0 tensor-API split.

Reference parity: python/paddle/tensor/creation.py (the 2.0 namespace
rework present in the snapshot). Thin categorized re-exports of the
mode-aware ops surface; implementations live in paddle_tpu.ops.
"""

from ..ops import to_tensor  # noqa: F401
from ..ops import zeros  # noqa: F401
from ..ops import ones  # noqa: F401
from ..ops import full  # noqa: F401
from ..ops import zeros_like  # noqa: F401
from ..ops import ones_like  # noqa: F401
from ..ops import full_like  # noqa: F401
from ..ops import arange  # noqa: F401
from ..ops import linspace  # noqa: F401
from ..ops import eye  # noqa: F401
from ..ops import diag  # noqa: F401
from ..ops import tril  # noqa: F401
from ..ops import triu  # noqa: F401
from ..ops import meshgrid  # noqa: F401
from ..ops import assign  # noqa: F401
from ..ops import empty  # noqa: F401
from ..ops import empty_like  # noqa: F401
from ..ops import diagflat  # noqa: F401
from ..ops import clone  # noqa: F401
