"""paddle_tpu.tensor.search — the 2.0 tensor-API split.

Reference parity: python/paddle/tensor/search.py (the 2.0 namespace
rework present in the snapshot). Thin categorized re-exports of the
mode-aware ops surface; implementations live in paddle_tpu.ops.
"""

from ..ops import argmax  # noqa: F401
from ..ops import argmin  # noqa: F401
from ..ops import argsort  # noqa: F401
from ..ops import searchsorted  # noqa: F401
from ..ops import topk  # noqa: F401
from ..ops import where  # noqa: F401
from ..ops import index_sample  # noqa: F401
from ..ops import nonzero  # noqa: F401
from ..ops import sort  # noqa: F401
from ..ops import index_select  # noqa: F401
from ..ops import mode  # noqa: F401
from ..ops import kthvalue  # noqa: F401
