"""paddle_tpu.tensor.random — the 2.0 tensor-API split.

Reference parity: python/paddle/tensor/random.py (the 2.0 namespace
rework present in the snapshot). Thin categorized re-exports of the
mode-aware ops surface; implementations live in paddle_tpu.ops.
"""

from ..ops import bernoulli  # noqa: F401
from ..ops import multinomial  # noqa: F401
from ..ops import normal  # noqa: F401
from ..ops import uniform  # noqa: F401
from ..ops import randn  # noqa: F401
from ..ops import rand  # noqa: F401
from ..ops import randint  # noqa: F401
from ..ops import randperm  # noqa: F401
from ..ops import poisson  # noqa: F401
from ..ops import standard_normal  # noqa: F401
