"""paddle_tpu.tensor.stat — the 2.0 tensor-API split.

Reference parity: python/paddle/tensor/stat.py (the 2.0 namespace
rework present in the snapshot). Thin categorized re-exports of the
mode-aware ops surface; implementations live in paddle_tpu.ops.
"""

from ..ops import mean  # noqa: F401
from ..ops import std  # noqa: F401
from ..ops import var  # noqa: F401
from ..ops import numel  # noqa: F401
from ..ops import median  # noqa: F401
from ..ops import nanmedian  # noqa: F401
from ..ops import quantile  # noqa: F401
