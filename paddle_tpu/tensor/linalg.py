"""paddle_tpu.tensor.linalg — the 2.0 tensor-API split.

Reference parity: python/paddle/tensor/linalg.py (the 2.0 namespace
rework present in the snapshot). Thin categorized re-exports of the
mode-aware ops surface; implementations live in paddle_tpu.ops.
"""

from ..ops import matmul  # noqa: F401
from ..ops import dot  # noqa: F401
from ..ops import norm  # noqa: F401
from ..ops import transpose  # noqa: F401
from ..ops import t  # noqa: F401
from ..ops import cross  # noqa: F401
from ..ops import cholesky  # noqa: F401
from ..ops import bmm  # noqa: F401
from ..ops import histogram  # noqa: F401
from ..ops import det  # noqa: F401
from ..ops import slogdet  # noqa: F401
from ..ops import matrix_power  # noqa: F401
from ..ops import qr  # noqa: F401
from ..ops import svd  # noqa: F401
from ..ops import pinv  # noqa: F401
from ..ops import solve  # noqa: F401
from ..ops import lstsq  # noqa: F401
from ..ops import matrix_rank  # noqa: F401
from ..ops import eig  # noqa: F401
from ..ops import eigh  # noqa: F401
from ..ops import inverse  # noqa: F401
from ..ops import triangular_solve  # noqa: F401
from ..ops import dist  # noqa: F401
from ..ops import mv  # noqa: F401
