"""paddle_tpu.tensor.attribute — the 2.0 tensor-API split.

Reference parity: python/paddle/tensor/attribute.py (the 2.0 namespace
rework present in the snapshot). Thin categorized re-exports of the
mode-aware ops surface; implementations live in paddle_tpu.ops.
"""

from ..ops import shape  # noqa: F401
from ..ops import real  # noqa: F401
from ..ops import imag  # noqa: F401
from ..ops import rank  # noqa: F401
from ..ops import is_complex  # noqa: F401
from ..ops import is_integer  # noqa: F401
