"""paddle_tpu.tensor.manipulation — the 2.0 tensor-API split.

Reference parity: python/paddle/tensor/manipulation.py (the 2.0 namespace
rework present in the snapshot). Thin categorized re-exports of the
mode-aware ops surface; implementations live in paddle_tpu.ops.
"""

from ..ops import cast  # noqa: F401
from ..ops import concat  # noqa: F401
from ..ops import expand  # noqa: F401
from ..ops import broadcast_to  # noqa: F401
from ..ops import expand_as  # noqa: F401
from ..ops import flatten  # noqa: F401
from ..ops import gather  # noqa: F401
from ..ops import gather_nd  # noqa: F401
from ..ops import reshape  # noqa: F401
from ..ops import flip  # noqa: F401
from ..ops import roll  # noqa: F401
from ..ops import scatter  # noqa: F401
from ..ops import scatter_nd_add  # noqa: F401
from ..ops import shard_index  # noqa: F401
from ..ops import slice  # noqa: F401
from ..ops import split  # noqa: F401
from ..ops import chunk  # noqa: F401
from ..ops import squeeze  # noqa: F401
from ..ops import stack  # noqa: F401
from ..ops import strided_slice  # noqa: F401
from ..ops import tile  # noqa: F401
from ..ops import transpose  # noqa: F401
from ..ops import unbind  # noqa: F401
from ..ops import unique  # noqa: F401
from ..ops import unsqueeze  # noqa: F401
from ..ops import unstack  # noqa: F401
from ..ops import repeat_interleave  # noqa: F401
from ..ops import index_select  # noqa: F401
from ..ops import masked_select  # noqa: F401
from ..ops import take_along_axis  # noqa: F401
from ..ops import pixel_shuffle  # noqa: F401
from ..ops import pixel_unshuffle  # noqa: F401
from ..ops import channel_shuffle  # noqa: F401
from ..ops import as_complex  # noqa: F401
from ..ops import as_real  # noqa: F401
from ..ops import reverse  # noqa: F401
from ..ops import scatter_nd  # noqa: F401
from ..ops import put_along_axis  # noqa: F401
