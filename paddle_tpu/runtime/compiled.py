"""The ONE compiled-callable runtime every dispatch site shares.

Before this module, AOT compile, CostRecord capture, LRU caching,
donation-retry discipline, and compile accounting were triplicated
across ``static/executor.py`` (jit-cache entries), ``framework/jit.py``
(``TrainStepFn._exec``), and ``generation/engine.py`` (``_compiled``) —
with per-site drift (executor LRU 128 vs TrainStepFn LRU 16, separate
unexpected-compile counters). TVM's lesson (PAPERS.md, arXiv
1802.04799) is that compilation policy belongs at one choke point;
this is it:

- **Cache key** — any hashable signature the caller derives from its
  avals; the store folds it into a short stable ``cache_key`` string
  (``<label>#<hex>``) that names the SAME identity everywhere: the
  CostRecord ledger, flight-recorder compile/demote events, and trace
  ``annotate()`` dispositions. A /tracez reader, a debug dump, and
  ``/costz`` all cite one id.
- **LRU bound** — ``FLAGS_compiled_cache_capacity`` governs every
  store (one knob, not N hardcoded constants); an eviction bumps
  ``<label>::cache_evict`` so silent recompile churn from an
  undersized cache is visible in the counters.
- **AOT lower+compile** — the same single XLA compile ``jax.jit``'s
  first call would pay, done once per entry under a double-checked
  per-entry lock (N serving workers racing one cold signature pay ONE
  compile) and captured into the cost model so MFU comes from what XLA
  actually built.
- **Demote-to-jit** — the AOT executable is stricter than ``jax.jit``
  (aval/layout drift raises where jit silently recompiles): a failed
  AOT dispatch demotes the entry to the jit path and retries — but
  NEVER after donation consumed input buffers, and the stale
  CostRecord is dropped so the MFU ledger can't credit pre-drift
  numbers against jit's recompile.
"""
from __future__ import annotations

import hashlib
import threading

import jax

from ..flags import flag
from ..profiler import bump_counter

__all__ = ["CompiledEntry", "CompiledStore", "CompileWatch",
           "any_deleted", "cache_capacity"]


def cache_capacity() -> int:
    """The shared executable-cache bound (``FLAGS_compiled_cache_capacity``),
    read at insert time so ``set_flags`` applies to live stores."""
    return max(1, int(flag("compiled_cache_capacity")))


def any_deleted(arrays) -> bool:
    """Whether any array's buffer has been consumed (donation): decides
    if a failed AOT dispatch may be retried on the jit fallback path."""
    for a in arrays:
        try:
            if a.is_deleted():
                return True
        except Exception:
            continue
    return False


class _NullCapture:
    """Stand-in when the tuning stack is unavailable: records nothing
    (entries then never schedule-refresh — plain caching)."""

    log: dict = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def _sched_capture():
    """Capture which kernel schedules a trace resolves
    (tuning/schedule.py capture_resolutions) — the per-entry record
    behind precise invalidation: a tuned swap-in rebuilds ONLY the
    signatures that actually baked the changed schedule in, never the
    whole fleet of compiled programs. Exception-safe: a broken tuning
    stack degrades to no capture, never a crash."""
    try:
        from ..tuning.schedule import capture_resolutions

        return capture_resolutions()
    except Exception:
        return _NullCapture()


def _schedules_stale(entry) -> bool:
    """Would any schedule this entry's trace resolved resolve
    DIFFERENTLY now? (Quiet — no tuner counters, no search enqueue.)"""
    rec = entry.resolved_schedules
    if not rec:
        return False  # resolved nothing (or not traced yet): immune
    try:
        from ..tuning.schedule import resolutions_stale

        return resolutions_stale(rec)
    except Exception:
        return False


class CompiledEntry:
    """One compiled program: the ``jax.jit`` callable plus its AOT slot.

    ``meta`` carries whatever the call site attached at build time
    (e.g. the executor's donate/hold name tuples). ``lock`` serializes
    the one-time AOT compile; ``attempted`` is the double-check."""

    __slots__ = ("sig", "cache_key", "jitted", "meta", "aot", "record",
                 "attempted", "lock", "resolved_schedules", "refresh_gen")

    def __init__(self, sig, cache_key, jitted, meta, refresh_gen=0):
        self.sig = sig
        self.cache_key = cache_key
        self.jitted = jitted
        self.meta = meta
        self.aot = None
        self.record = None
        self.attempted = False
        self.lock = threading.Lock()
        # which kernel schedules the trace resolved (captured at first
        # lower/dispatch): the precise-invalidation record — None until
        # traced, {} if the program resolves no tuned kernel
        self.resolved_schedules = None
        # bumps each time this signature is rebuilt for a schedule
        # swap, so the refreshed compile gets a NEW cost identity
        self.refresh_gen = refresh_gen


class CompiledStore:
    """LRU cache of :class:`CompiledEntry` + the dispatch discipline.

    ``label`` prefixes counters and cache keys; ``cost_label`` is the
    CostRecord label (``cost_model.latest_record(cost_label)``).
    ``hit_counter``/``miss_counter`` are optional profiler counter names
    bumped on lookup (the executor keeps its historical
    ``executor::jit_cache_hit/miss`` names through these; generation
    routes its ``generation::compile`` count through ``miss_counter``).
    ``capacity`` overrides the flag-governed bound (tests only).
    """

    def __init__(self, label, *, cost_label=None, capacity=None,
                 hit_counter=None, miss_counter=None):
        self.label = label
        self.cost_label = cost_label or label
        self._capacity = capacity
        self._hit_counter = hit_counter
        self._miss_counter = miss_counter
        self._entries: dict = {}
        self._lock = threading.Lock()

    # -- cache -------------------------------------------------------------

    @property
    def capacity(self) -> int:
        return (self._capacity if self._capacity is not None
                else cache_capacity())

    @capacity.setter
    def capacity(self, value):
        self._capacity = None if value is None else int(value)

    def __len__(self):
        return len(self._entries)

    def entries(self) -> dict:
        """Snapshot of sig -> CompiledEntry (insertion = LRU order)."""
        with self._lock:
            return dict(self._entries)

    def mapping(self) -> "EntriesView":
        """A LIVE mutable view over the cache (``clear``/``del`` force
        recompiles on the next lookup) — the legacy ``Executor._cache``
        surface."""
        return EntriesView(self)

    def drop(self, sig):
        """Invalidate one signature (next lookup recompiles)."""
        with self._lock:
            return self._entries.pop(sig, None)

    def clear(self):
        with self._lock:
            self._entries.clear()

    def _key_of(self, sig, refresh_gen=0) -> str:
        ident = sig if refresh_gen == 0 else (sig, refresh_gen)
        h = hashlib.sha1(repr(ident).encode()).hexdigest()[:10]
        return f"{self.label}#{h}"

    def get_or_build(self, sig, build):
        """Look up (or build) the entry for ``sig``.

        ``build()`` -> ``(jitted_callable, meta)`` runs under the store
        lock on a miss (entry creation must be atomic so two threads
        racing a cold signature share ONE entry — the per-entry lock
        then serializes the actual XLA compile). Returns
        ``(entry, "hit" | "miss")``.

        Kernel-autotuner coupling: each entry records which schedules
        its trace resolved; when any of them would resolve differently
        NOW (a tuned swap-in, a ``FLAGS_kernel_autotune`` flip), the
        entry is invalidated here — counted as
        ``<label>::schedule_refresh`` — so the swap is a clean
        recompile, never a stale trace. Signatures that resolve no
        tuned kernel are immune (no fleet-wide recompile waves).
        """
        with self._lock:
            entry = self._entries.get(sig)
            refresh_gen = 0
            if entry is not None and _schedules_stale(entry):
                self._entries.pop(sig)
                refresh_gen = entry.refresh_gen + 1
                bump_counter(f"{self.label}::schedule_refresh")
                _flight().record_event(
                    "runtime_schedule_refresh", label=self.label,
                    cache_key=entry.cache_key)
                entry = None
            if entry is not None:
                self._entries[sig] = self._entries.pop(sig)  # refresh LRU
                if self._hit_counter:
                    bump_counter(self._hit_counter)
                return entry, "hit"
            if self._miss_counter:
                bump_counter(self._miss_counter)
            jitted, meta = build()
            entry = CompiledEntry(sig, self._key_of(sig, refresh_gen),
                                  jitted, meta, refresh_gen=refresh_gen)
            self._entries[sig] = entry
            cap = self.capacity
            while len(self._entries) > cap:
                evicted = self._entries.pop(next(iter(self._entries)))
                # an eviction means the NEXT dispatch of that signature
                # recompiles: silent churn from an undersized cache must
                # show in the counters (FLAGS_compiled_cache_capacity is
                # the knob)
                bump_counter(f"{self.label}::cache_evict")
                _flight().record_event(
                    "runtime_cache_evict", label=self.label,
                    cache_key=evicted.cache_key, capacity=cap)
        return entry, "miss"

    # -- dispatch ----------------------------------------------------------

    def _aot_compile(self, entry, args, capture_meta):
        """One-time AOT lower+compile (the same work jax.jit's first
        call would do) so the compiled module's own cost_analysis /
        memory_analysis land in the cost-model registry — utilization
        from what XLA actually built, not an estimate. Double-checked
        under the per-entry lock: a second worker on the same cold
        signature waits for the executable instead of recompiling."""
        from ..monitor import cost_model as _cost
        from ..monitor import goodput as _goodput

        with entry.lock:
            if entry.attempted:
                return
            try:
                # trace + XLA compile are badput in the goodput ledger's
                # taxonomy: a span here covers both, and the ledger
                # deducts it from the enclosing step frame's compute.
                # The named_scope prefixes every op stamp the traced
                # function emits (executor._exec_one's opprof stamps)
                # with this store's label, so a device-trace row reads
                # executor/matmul#0/3/... and attribution can tell which
                # runtime (executor, serving replica, ...) issued the op.
                with _goodput.span("compile"), _sched_capture() as cap, \
                        jax.named_scope(self.label):
                    lowered = entry.jitted.lower(*args)
                # the trace just ran: record the schedules it baked in
                entry.resolved_schedules = dict(cap.log or {})
                with _goodput.span("compile"):
                    entry.aot = lowered.compile()
                entry.record = _cost.capture(
                    self.cost_label, lowered=lowered, compiled=entry.aot,
                    key=entry.cache_key, cache_key=entry.cache_key,
                    **(capture_meta or {}))
                _flight().record_event(
                    "runtime_compile", label=self.label,
                    cache_key=entry.cache_key,
                    flops=entry.record.flops if entry.record else 0.0)
            except Exception:
                entry.aot = None  # backend without the AOT surface: jit
            entry.attempted = True

    def dispatch(self, entry, *args, donated=(), capture_meta=None):
        """Run one compiled call through the shared discipline.

        ``donated`` names the arrays whose buffers the call may consume
        (sequence, or a zero-arg callable evaluated only on failure):
        the demote-to-jit retry is forbidden once any is consumed.
        Annotates the current trace span with the entry's ``cache_key``
        (+ FLOPs when captured) and feeds the executed-work ledger.
        """
        from ..monitor import cost_model as _cost
        from ..monitor import tracing as _tracing

        if not entry.attempted:
            self._aot_compile(entry, args, capture_meta)
        runner = entry.aot if entry.aot is not None else entry.jitted
        try:
            if entry.resolved_schedules is None:
                # AOT lowering was unavailable: the jit fallback's first
                # call traces here — capture its schedule resolutions
                with _sched_capture() as cap:
                    out = runner(*args)
                entry.resolved_schedules = dict(cap.log or {})
            else:
                out = runner(*args)
        except Exception:
            consumed = donated() if callable(donated) else donated
            if runner is entry.jitted or any_deleted(consumed):
                raise
            # demote: jax.jit recompiles for the drifted avals; the
            # captured record no longer describes what runs, so drop it
            # (crediting it would silently corrupt the MFU ledger)
            entry.aot = None
            entry.record = None
            bump_counter(f"{self.label}::aot_demote")
            _flight().record_event(
                "runtime_demote", label=self.label,
                cache_key=entry.cache_key)
            out = entry.jitted(*args)
        _cost.note_run(entry.record)
        if entry.record is not None:
            # the cost sheet makes the trace self-contained: a /tracez
            # reader sees what the dispatch COST under the same identity
            # the CostRecord ledger uses
            _tracing.annotate(cache_key=entry.cache_key,
                              flops=entry.record.flops,
                              cost_bytes=entry.record.bytes_accessed)
        else:
            _tracing.annotate(cache_key=entry.cache_key)
        return out


class EntriesView:
    """Live dict-like view over a store's entries. Reads see current
    state; ``clear()``/``del view[sig]``/``pop`` invalidate entries in
    the REAL cache (the next lookup recompiles) — preserving the
    mutation semantics the pre-runtime ``Executor._cache`` dict had."""

    __slots__ = ("_store",)

    def __init__(self, store):
        self._store = store

    def _snap(self):
        return self._store.entries()

    def __len__(self):
        return len(self._store)

    def __iter__(self):
        return iter(self._snap())

    def __contains__(self, sig):
        return sig in self._snap()

    def __getitem__(self, sig):
        entry = self._snap().get(sig)
        if entry is None:
            raise KeyError(sig)
        return entry

    def __delitem__(self, sig):
        if self._store.drop(sig) is None:
            raise KeyError(sig)

    def get(self, sig, default=None):
        return self._snap().get(sig, default)

    def pop(self, sig, *default):
        entry = self._store.drop(sig)
        if entry is None:
            if default:
                return default[0]
            raise KeyError(sig)
        return entry

    def clear(self):
        self._store.clear()

    def keys(self):
        return self._snap().keys()

    def values(self):
        return self._snap().values()

    def items(self):
        return self._snap().items()

    def __repr__(self):
        return f"EntriesView({self._snap()!r})"


def _flight():
    # lazy: the monitor package imports flags early in bootstrap; this
    # module must stay importable before monitor finishes initializing
    from ..monitor import flight_recorder

    return flight_recorder


class CompileWatch:
    """Warmup-snapshot compile accounting (serving pool, generation
    engine, and any future steady-state-bounded dispatch site).

    ``arm()`` after warmup snapshots a compile counter (read through
    ``read``); any later growth is an UNEXPECTED compile — the bounded-
    compile invariant broke — counted loudly into ``metric`` plus a
    flight-recorder event instead of silently re-growing the cache.
    ``note()`` is an atomic read-compare-bump: N workers may observe the
    same miss concurrently and it must count once.
    """

    def __init__(self, read, metric="serving/unexpected_compiles",
                 event="serving_unexpected_compile"):
        from ..monitor import counter

        self._read = read
        self._event = event
        self._baseline = None
        self._seen = 0
        self._metric = counter(metric)
        self._lock = threading.Lock()

    def arm(self):
        self._baseline = self._read()
        self._seen = 0
        return self

    @property
    def armed(self) -> bool:
        return self._baseline is not None

    def extra(self) -> int:
        """Compiles since ``arm()`` — steady state must keep this 0."""
        if self._baseline is None:
            from ..errors import PreconditionNotMetError

            raise PreconditionNotMetError(
                "extra_compiles() before warmup(): nothing to compare")
        return self._read() - self._baseline

    def note(self, **fields):
        """Record any NEW growth since the last note (no-op when flat)."""
        with self._lock:
            extra = self.extra()
            grew = extra - self._seen
            if grew <= 0:
                return
            self._seen = extra
            self._metric.inc(grew)
            _flight().record_event(self._event, total=extra, **fields)
