"""Shared compiled-callable runtime.

One module owns the lifecycle every compiled dispatch site used to
re-implement: cache-key construction, the one-time AOT lower+compile
(double-checked per-entry lock), CostRecord capture, the LRU-bounded
executable cache, the donation-safe demote-to-jit fallback, and
recompile/unexpected-compile accounting. ``static/executor.py``,
``framework/jit.py`` (TrainStepFn), and the generation engine all
dispatch through :class:`runtime.compiled.CompiledStore`, so a speed or
correctness change here reaches every workload at once.
"""
from .compiled import (  # noqa: F401
    CompiledEntry,
    CompiledStore,
    CompileWatch,
    any_deleted,
    cache_capacity,
)

__all__ = [
    "CompiledEntry",
    "CompiledStore",
    "CompileWatch",
    "any_deleted",
    "cache_capacity",
]
