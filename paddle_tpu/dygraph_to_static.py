"""Dygraph-to-static AST transforms (value-dependent control flow).

Reference parity: python/paddle/fluid/dygraph/dygraph_to_static/ — the
AST transformer stack (ifelse_transformer.py, loop_transformer.py,
logical_transformer.py, program_translator.py). The reference rewrites
Python `if`/`while`/`and`/`or` over Variables into conditional_block /
while ops; here they rewrite into runtime converter calls that dispatch
on tracedness:

- concrete (eager) values  → plain Python control flow, unchanged
  semantics;
- traced values (inside a compiled step / to_static trace) →
  lax.cond / lax.while_loop / jnp.logical_*, which is how XLA wants
  data-dependent control flow expressed.

Supported v1 surface (unsupported shapes are left untouched and only
fail if the predicate is actually traced, with a clear message):

- ``if``/``elif``/``else`` whose branches assign local names (the
  modified names become the merged outputs) or where both branches end
  in ``return``;
- ``while`` loops whose body assigns local names (the loop carry);
- ``and`` / ``or`` / ``not`` inside the transformed function.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .framework.tensor import Tensor

__all__ = [
    "convert_ifelse",
    "convert_while_loop",
    "convert_logical_and",
    "convert_logical_or",
    "convert_logical_not",
    "convert_print",
    "convert_assert",
    "convert_cast",
    "convert_to_static",
    "UNDEF",
]


class _Undefined:
    """Sentinel for names not yet bound when a transformed control-flow
    region starts (the reference's UndefinedVar,
    dygraph_to_static/variable_trans_func.py)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<paddle_tpu UNDEF>"

    def __bool__(self):
        raise NameError(
            "variable is used before assignment inside transformed "
            "control flow"
        )


UNDEF = _Undefined()


# ---------------------------------------------------------------------------
# runtime converters (dygraph_to_static/convert_operators.py equivalents)
# ---------------------------------------------------------------------------


def _arr(v):
    return v._array if isinstance(v, Tensor) else v


def _is_traced(v):
    return isinstance(_arr(v), jax.core.Tracer)


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        _arr, tree, is_leaf=lambda x: isinstance(x, Tensor)
    )


def _canon(a):
    """Canonicalize python/weak scalar leaves to strong-typed arrays so
    lax.cond branch outputs and lax.while carries unify (a flag assigned
    ``True`` in one branch must match the carried bool[] in the other)."""
    if isinstance(a, (bool, int, float)) or (
        hasattr(a, "weak_type") and a.weak_type and getattr(a, "ndim", None) == 0
    ):
        arr = jnp.asarray(a)
        return lax.convert_element_type(arr, arr.dtype)  # strips weak_type
    return a


def _canon_tree(tree):
    return jax.tree_util.tree_map(_canon, tree)


def _rewrap_like(arrays, template):
    # None/UNDEF kept as leaves on both sides so positions stay aligned
    # when a branch merge produced a placeholder for a missing value
    is_leaf = lambda x: isinstance(x, Tensor) or x is None or x is UNDEF  # noqa: E731
    flat_t, treedef = jax.tree_util.tree_flatten(template, is_leaf=is_leaf)
    flat_a, _ = jax.tree_util.tree_flatten(arrays, is_leaf=is_leaf)
    out = [
        Tensor._from_array(a) if isinstance(t, Tensor) and a is not None else a
        for a, t in zip(flat_a, flat_t)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def convert_ifelse(pred, true_fn, false_fn, args=()):
    """ifelse_transformer target: branch on a maybe-traced predicate.

    ``args`` are the branch-local carries (names the branches modify),
    passed as parameters so self-referential updates like ``s = s + x``
    read the pre-branch value instead of an unbound closure local.
    """
    if not _is_traced(pred):
        p = _arr(pred)
        taken = bool(np.asarray(p)) if hasattr(p, "dtype") else bool(p)
        return true_fn(*args) if taken else false_fn(*args)
    p = jnp.reshape(_arr(pred), ()).astype(bool)

    # trace both branches; unify pytrees of Tensors/arrays. The first
    # trace of true_fn doubles as the Tensor-vs-array structure template
    # (no extra call — branches may be expensive to trace).
    sample = [None]

    def _missing(v):
        return v is None or v is UNDEF

    def mk(fn, capture=False, specs=None):
        def f(_):
            out = fn(*args)
            if capture:
                sample[0] = out
            res = _canon_tree(_unwrap_tree(out))
            if specs is not None:
                flat, td = jax.tree_util.tree_flatten(res, is_leaf=_missing)
                flat = [
                    (jnp.zeros(s.shape, s.dtype) if s is not None else None)
                    if _missing(x)
                    else (
                        x.astype(s.dtype)
                        if s is not None and hasattr(x, "astype")
                        and x.dtype != s.dtype else x
                    )
                    for x, s in zip(flat, specs)
                ]
                res = jax.tree_util.tree_unflatten(td, flat)
            return res
        return f

    def probe(fn):
        """Abstractly trace a branch, tolerating missing (None/UNDEF)
        leaves: returns (treedef, [spec-or-None per leaf])."""
        store = {}

        def g(_):
            res = _canon_tree(_unwrap_tree(fn(*args)))
            flat, td = jax.tree_util.tree_flatten(res, is_leaf=_missing)
            store["td"] = td
            store["missing"] = [_missing(x) for x in flat]
            return tuple(
                jnp.zeros((), jnp.float32) if _missing(x) else x
                for x in flat
            )

        ab = jax.eval_shape(g, None)
        return store["td"], [
            None if m else s for m, s in zip(store["missing"], ab)
        ]

    try:
        out = lax.cond(p, mk(true_fn, capture=True), mk(false_fn), None)
    except TypeError:
        # branch unification (the reference's RETURN_NO_VALUE /
        # variable_trans_func merging): dtype drift (`i + 1` promoting an
        # int32 carry under x64) unifies to the promoted dtype; a missing
        # value in one branch (early-return value / name unbound on the
        # not-taken path) gets a dead-path zero placeholder. Anything else
        # still raises loudly.
        td_t, specs_t = probe(true_fn)
        td_f, specs_f = probe(false_fn)
        if td_t != td_f:
            raise
        specs = []
        for a, b in zip(specs_t, specs_f):
            if a is None and b is None:
                specs.append(None)
            elif a is None or b is None:
                specs.append(b if a is None else a)
            else:
                if a.shape != b.shape:
                    raise
                specs.append(jax.ShapeDtypeStruct(
                    a.shape, jnp.promote_types(a.dtype, b.dtype)
                ))
        out = lax.cond(
            p, mk(true_fn, capture=True, specs=specs),
            mk(false_fn, specs=specs), None,
        )
    return _rewrap_like(out, sample[0])


def convert_while_loop(cond_fn, body_fn, loop_vars):
    """loop_transformer target: while over a maybe-traced condition.

    Note the XLA contract: a traced while_loop is not reverse-
    differentiable (use the scan construct for trainable loops).
    """
    if any(v is UNDEF for v in loop_vars) and not any(
        _is_traced(v) for v in loop_vars if v is not UNDEF
    ):
        # a name assigned inside the loop but unbound before it: in the
        # python path it binds on the first iteration. (In the traced path
        # below, the placeholder probe seeds it — or UNDEF.__bool__ raises
        # a clear NameError if the body reads it before assignment.)
        env = list(loop_vars)
        while bool(np.asarray(_arr(cond_fn(*env)))):
            out = body_fn(*env)
            env = list(out) if isinstance(out, tuple) else [out]
        return tuple(env) if len(env) > 1 else env[0]

    first = cond_fn(*loop_vars)
    if not _is_traced(first) and not any(_is_traced(v) for v in loop_vars):
        vars_ = tuple(loop_vars)
        while bool(np.asarray(_arr(cond_fn(*vars_)))):
            out = body_fn(*vars_)
            vars_ = tuple(out) if isinstance(out, tuple) else (out,)
        return vars_ if len(vars_) > 1 else vars_[0]

    template = tuple(loop_vars)
    init = tuple(_canon(_arr(v)) for v in loop_vars)

    def cond(c):
        vs = _rewrap_like(c, template)
        return jnp.reshape(_arr(cond_fn(*vs)), ()).astype(bool)

    def body(c):
        vs = _rewrap_like(c, template)
        out = body_fn(*vs)
        out = out if isinstance(out, tuple) else (out,)
        return tuple(_canon(_arr(v)) for v in out)

    # a missing carry (None/UNDEF — e.g. an early-return value assigned
    # only inside the loop): probe one body step for its concrete spec and
    # seed a dead-path zero placeholder, mirroring the reference's
    # fill_constant placeholder vars (variable_trans_func.py)
    missing = [
        i for i, v in enumerate(init) if v is None or v is UNDEF
    ]
    if missing:
        def _probe_body():
            out = body_fn(*template)
            out = out if isinstance(out, tuple) else (out,)
            flat = [_arr(v) for v in out]
            return tuple(
                jnp.zeros((), jnp.float32)
                if (x is None or x is UNDEF) else x
                for x in flat
            )

        ab = jax.eval_shape(_probe_body)
        init = tuple(
            jnp.zeros(ab[i].shape, ab[i].dtype) if i in missing else v
            for i, v in enumerate(init)
        )

    # unify carry dtypes with what one body step produces (e.g. `i + 1`
    # promoting an int32 init to int64 under x64); iterate to a fixpoint
    # since promoting the init can promote further body outputs
    for _ in range(3):
        out_shapes = jax.tree_util.tree_leaves(jax.eval_shape(body, init))
        changed = False
        new_init = []
        for a, s in zip(init, out_shapes):
            arr = jnp.asarray(a)
            if arr.dtype != s.dtype:
                pd = jnp.promote_types(arr.dtype, s.dtype)
                if pd != arr.dtype:
                    arr = arr.astype(pd)
                    changed = True
            new_init.append(arr)
        init = tuple(new_init)
        if not changed:
            break

    final = lax.while_loop(cond, body, init)
    out = _rewrap_like(final, template)
    return out if len(template) > 1 else out[0]


def convert_logical_and(x_fn, y_fn):
    x = x_fn()
    if not _is_traced(x):
        xa = _arr(x)
        if hasattr(xa, "dtype") and np.asarray(xa).size == 1:
            if not bool(np.asarray(xa)):
                return x  # python short-circuit semantics
            return y_fn()
        if not hasattr(xa, "dtype"):
            return x and y_fn()
    y = y_fn()
    return Tensor._from_array(
        jnp.logical_and(
            jnp.asarray(_arr(x)).astype(bool),
            jnp.asarray(_arr(y)).astype(bool),
        )
    )


def convert_logical_or(x_fn, y_fn):
    x = x_fn()
    if not _is_traced(x):
        xa = _arr(x)
        if hasattr(xa, "dtype") and np.asarray(xa).size == 1:
            if bool(np.asarray(xa)):
                return x
            return y_fn()
        if not hasattr(xa, "dtype"):
            return x or y_fn()
    y = y_fn()
    return Tensor._from_array(
        jnp.logical_or(
            jnp.asarray(_arr(x)).astype(bool),
            jnp.asarray(_arr(y)).astype(bool),
        )
    )


def convert_logical_not(x):
    if not _is_traced(x) and not hasattr(_arr(x), "dtype"):
        return not x
    return Tensor._from_array(jnp.logical_not(
        jnp.asarray(_arr(x)).astype(bool)
    ))


_CALLBACK_SUPPORT = {}
_CALLBACK_WARNED = set()


def _callbacks_supported():
    """Whether the default backend can run host callbacks
    (jax.debug.print/callback). The axon-tunneled TPU backend rejects
    host send/recv with UNIMPLEMENTED at run time, so probe once with a
    tiny jitted program and cache per platform."""
    platform = jax.default_backend()
    if platform not in _CALLBACK_SUPPORT:
        try:
            v = jax.jit(
                lambda x: jax.debug.callback(lambda _: None, x) or x
            )(jnp.zeros(()))
            jax.block_until_ready(v)
            _CALLBACK_SUPPORT[platform] = True
        except Exception:
            _CALLBACK_SUPPORT[platform] = False
    return _CALLBACK_SUPPORT[platform]


def _warn_no_callbacks(what):
    import warnings

    platform = jax.default_backend()
    key = (platform, what)
    if key not in _CALLBACK_WARNED:
        _CALLBACK_WARNED.add(key)
        warnings.warn(
            f"traced {what} skipped: backend {platform!r} does not "
            "support host callbacks (jax.debug.*); values are not "
            "observable from compiled code on this backend",
            RuntimeWarning, stacklevel=3,
        )


def convert_print(*args, **kwargs):
    """print_transformer target (dygraph_to_static/print_transformer.py):
    a print over traced values becomes a device-side debug print (the
    reference lowers to the Print op); plain python print otherwise.
    The traced path honors sep/end (jax.debug.print emits one line per
    call, so a non-default end is appended into the payload); the file
    kwarg only applies on the python path. On backends without host
    callbacks (the axon TPU tunnel) a traced print degrades to a
    one-time trace-time warning instead of an UNIMPLEMENTED crash — the
    reference's Print op is best-effort logging too."""
    if any(_is_traced(a) for a in args):
        if not _callbacks_supported():
            _warn_no_callbacks("print")
            return
        esc = lambda s: s.replace("{", "{{").replace("}", "}}")  # noqa: E731
        sep = esc(kwargs.get("sep", " "))
        end = kwargs.get("end", "\n")
        fmt = sep.join(["{}"] * len(args))
        if end != "\n":
            fmt += esc(end)
        jax.debug.print(fmt, *[_arr(a) for a in args])
    else:
        print(*args, **kwargs)


def convert_assert(cond, msg=None):
    """assert_transformer target: a traced assert becomes a host callback
    that raises when the condition is false at run time (the reference's
    Assert op PADDLE_ENFORCEs in-kernel); eager asserts stay python.

    On backends without host callbacks (the axon TPU tunnel) the runtime
    check cannot exist inside the compiled program; the assert degrades
    to a one-time warning (use FLAGS_check_nan_inf's checkify path for
    in-program numeric guards there)."""
    if not _is_traced(cond):
        c = _arr(cond)
        ok = bool(np.asarray(c)) if hasattr(c, "dtype") else bool(c)
        if not ok:
            raise AssertionError(msg if msg is not None else "assert failed")
        return

    if not _callbacks_supported():
        _warn_no_callbacks("assert")
        return

    def _check(ok):
        if not bool(np.asarray(ok)):
            raise AssertionError(
                msg if msg is not None
                else "Assert failed inside compiled function"
            )

    jax.debug.callback(_check, jnp.reshape(_arr(cond), ()).astype(bool))


_CAST_DTYPES = {"int": "int64", "float": "float32", "bool": "bool"}


def convert_cast(ty, x):
    """cast_transformer target: int(x)/float(x)/bool(x)/len(x) over a
    traced tensor become dtype casts / static shape reads (the reference
    rewrites them to cast ops); python builtins otherwise."""
    if ty == "len":
        a = _arr(x)
        if hasattr(a, "shape") and getattr(a, "ndim", 0) >= 1:
            return a.shape[0]  # shapes are static under XLA tracing
        return len(x)
    if _is_traced(x):
        return Tensor._from_array(_arr(x).astype(_CAST_DTYPES[ty]))
    return {"int": int, "float": float, "bool": bool}[ty](x)


# ---------------------------------------------------------------------------
# AST transformer (ifelse_transformer.py / loop_transformer.py)
# ---------------------------------------------------------------------------


def _assign_const(name, value):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=ast.Constant(value))


def _flag_guard(flags, body):
    """``if not (f1 or f2): body`` — skip-the-rest guard shared by the
    return and break/continue transformers."""
    test = ast.Name(id=flags[0], ctx=ast.Load())
    if len(flags) > 1:
        test = ast.BoolOp(
            op=ast.Or(),
            values=[ast.Name(id=f, ctx=ast.Load()) for f in flags],
        )
    return ast.If(
        test=ast.UnaryOp(op=ast.Not(), operand=test),
        body=body or [ast.Pass()], orelse=[],
    )


def _scan_bc(stmts):
    """(has_break, has_continue) bound to the CURRENT loop: descends ifs
    and with/try blocks but not nested loops or function scopes."""
    has_b = has_c = False
    for s in stmts:
        if isinstance(s, ast.Break):
            has_b = True
        elif isinstance(s, ast.Continue):
            has_c = True
        elif isinstance(s, ast.If):
            for blk in (s.body, s.orelse):
                b, c = _scan_bc(blk)
                has_b |= b
                has_c |= c
        elif isinstance(s, ast.With):
            b, c = _scan_bc(s.body)
            has_b |= b
            has_c |= c
        elif isinstance(s, ast.Try):
            for blk in [s.body, s.orelse, s.finalbody] + [h.body for h in s.handlers]:
                b, c = _scan_bc(blk)
                has_b |= b
                has_c |= c
    return has_b, has_c


def _bc_only_under_ifs(stmts):
    """True when every current-loop break/continue sits under plain
    if/else nesting (the supported shape); with/try wrapping keeps python
    semantics."""
    for s in stmts:
        if isinstance(s, (ast.With, ast.Try)):
            blks = [getattr(s, "body", [])]
            if isinstance(s, ast.Try):
                blks += [s.orelse, s.finalbody] + [h.body for h in s.handlers]
            if any(any(_scan_bc(b)) for b in blks):
                return False
        elif isinstance(s, ast.If):
            if not (_bc_only_under_ifs(s.body) and _bc_only_under_ifs(s.orelse)):
                return False
    return True


def _is_range_for(node):
    return (
        isinstance(node.target, ast.Name)
        and isinstance(node.iter, ast.Call)
        and isinstance(node.iter.func, ast.Name)
        and node.iter.func.id == "range"
        and not node.iter.keywords
        and 1 <= len(node.iter.args) <= 3
    )


def _range_for_to_while(node, uid):
    """Desugar ``for i in range(...)`` to the explicit while form (the
    loop_transformer.py for→while lowering), shared by the break/continue
    and control-flow phases so both see identical loop-variable semantics.
    Returns (prelude_stmts, while_node) or None when the step is
    dynamic/negative (python semantics kept)."""
    args = node.iter.args
    start = args[0] if len(args) >= 2 else ast.Constant(0)
    stop = args[1] if len(args) >= 2 else args[0]
    step = args[2] if len(args) == 3 else ast.Constant(1)
    if len(args) == 3 and not (
        isinstance(step, ast.Constant) and isinstance(step.value, int)
        and step.value > 0
    ):
        return None
    it = f"_pt_for_{uid}"
    stop_name = f"_pt_stop_{uid}"
    init = ast.Assign(targets=[ast.Name(id=it, ctx=ast.Store())],
                      value=start)
    # snapshot the bound: python evaluates range() args exactly once, so a
    # body that mutates the bound variable must not change the trip count
    init_stop = ast.Assign(
        targets=[ast.Name(id=stop_name, ctx=ast.Store())], value=stop
    )
    # pre-bind the loop target ONLY if currently unbound (an empty range
    # must not clobber a prior value) — it then is a well-defined XLA
    # loop carry
    pre_bind = ast.Try(
        body=[ast.Assign(
            targets=[ast.Name(id=node.target.id, ctx=ast.Store())],
            value=ast.Name(id=node.target.id, ctx=ast.Load()),
        )],
        handlers=[ast.ExceptHandler(
            type=ast.Name(id="NameError", ctx=ast.Load()), name=None,
            body=[ast.Assign(
                targets=[ast.Name(id=node.target.id, ctx=ast.Store())],
                value=ast.Name(id=it, ctx=ast.Load()),
            )],
        )],
        orelse=[], finalbody=[],
    )
    test = ast.Compare(
        left=ast.Name(id=it, ctx=ast.Load()), ops=[ast.Lt()],
        comparators=[ast.Name(id=stop_name, ctx=ast.Load())],
    )
    bind = ast.Assign(
        targets=[node.target], value=ast.Name(id=it, ctx=ast.Load())
    )
    bump = ast.AugAssign(
        target=ast.Name(id=it, ctx=ast.Store()), op=ast.Add(), value=step
    )
    loop = ast.While(test=test, body=[bind] + node.body + [bump], orelse=[])
    return [init, init_stop, pre_bind], loop


class _ReturnTransformer(ast.NodeTransformer):
    """Early/mid-function returns (return_transformer.py): every
    ``return e`` becomes ``retv = e; retf = True`` (plus ``break`` when
    inside a loop), statements after a maybe-returning construct are
    guarded by ``if not retf``, and the function ends with a single
    ``return retv`` — so traced conditionals can merge return paths."""

    _counter = [0]

    def visit_FunctionDef(self, node):
        self.generic_visit(node)  # nested defs get their own flags first
        rets = [
            s for stmt in node.body for s in _walk_same_scope(stmt)
            if isinstance(s, ast.Return)
        ]
        if not rets or (len(rets) == 1 and node.body[-1] is rets[0]):
            return node
        self._counter[0] += 1
        uid = self._counter[0]
        flag, val = f"_pt_retf_{uid}", f"_pt_retv_{uid}"
        new_body, _ = self._rewrite(list(node.body), flag, val, in_loop=False)
        node.body = (
            [_assign_const(flag, False), _assign_const(val, None)]
            + new_body
            + [ast.Return(value=ast.Name(id=val, ctx=ast.Load()))]
        )
        ast.fix_missing_locations(node)
        return node

    @staticmethod
    def _contains_return(stmt):
        return any(isinstance(s, ast.Return) for s in _walk_same_scope(stmt))

    def _rewrite(self, stmts, flag, val, in_loop):
        out = []
        for i, s in enumerate(stmts):
            rest = stmts[i + 1:]
            if isinstance(s, ast.Return):
                out.append(ast.Assign(
                    targets=[ast.Name(id=val, ctx=ast.Store())],
                    value=s.value or ast.Constant(None),
                ))
                out.append(_assign_const(flag, True))
                if in_loop:
                    out.append(ast.Break())
                return out, True  # statements after a return are dead
            if isinstance(s, ast.If) and self._contains_return(s):
                s.body = self._rewrite(s.body, flag, val, in_loop)[0] or [ast.Pass()]
                s.orelse = self._rewrite(s.orelse, flag, val, in_loop)[0]
                out.append(s)
                if rest:
                    out.append(_flag_guard(
                        [flag], self._rewrite(rest, flag, val, in_loop)[0]
                    ))
                return out, True
            if isinstance(s, (ast.While, ast.For)) and self._contains_return(s):
                s.body = self._rewrite(s.body, flag, val, in_loop=True)[0]
                out.append(s)
                if in_loop:
                    # the return exited the INNER loop via break; the
                    # enclosing loop must stop too, or later outer
                    # iterations would overwrite the return value
                    out.append(ast.If(
                        test=ast.Name(id=flag, ctx=ast.Load()),
                        body=[ast.Break()], orelse=[],
                    ))
                if rest:
                    out.append(_flag_guard(
                        [flag], self._rewrite(rest, flag, val, in_loop)[0]
                    ))
                return out, True
            out.append(s)
        return out, False


class _BreakContinueTransformer(ast.NodeTransformer):
    """break/continue desugaring (break_continue_transformer.py):
    ``break`` sets a flag that both guards the rest of the iteration and
    joins the loop condition; ``continue`` sets a per-iteration flag that
    guards the rest of the iteration. The flag form contains no
    break/continue, so the control-flow transformer can lower the loop to
    lax.while_loop when values are traced."""

    _counter = [0]

    def visit_While(self, node):
        self.generic_visit(node)  # inner loops first
        has_b, has_c = _scan_bc(node.body)
        if not (has_b or has_c) or node.orelse:
            return node
        if not _bc_only_under_ifs(node.body):
            return node  # with/try-wrapped: keep python semantics
        self._counter[0] += 1
        uid = self._counter[0]
        brk = f"_pt_brk_{uid}" if has_b else None
        cnt = f"_pt_cnt_{uid}" if has_c else None
        new_body = self._rewrite(list(node.body), brk, cnt)
        prelude = []
        if cnt:
            new_body = [_assign_const(cnt, False)] + new_body
            # pre-loop binding so the flag is a well-formed XLA loop carry
            prelude.append(_assign_const(cnt, False))
        if brk:
            prelude.append(_assign_const(brk, False))
            node.test = ast.BoolOp(op=ast.And(), values=[
                ast.UnaryOp(op=ast.Not(),
                            operand=ast.Name(id=brk, ctx=ast.Load())),
                node.test,
            ])
        node.body = new_body
        out = prelude + [node]
        for x in out:
            ast.copy_location(x, node)
            ast.fix_missing_locations(x)
        return out

    def visit_For(self, node):
        self.generic_visit(node)
        has_b, has_c = _scan_bc(node.body)
        if not (has_b or has_c) or node.orelse:
            return node
        # only the range() form lowers further (the control-flow phase's
        # visit_For); anything else keeps python break/continue semantics
        # (incl. generators, which must not be exhausted past the break)
        if not _is_range_for(node):
            return node
        if not _bc_only_under_ifs(node.body):
            return node
        # two-phase: rewrite CONTINUE first, inside the for body only, so
        # the loop-variable bump added by the while desugar is NOT skipped
        # (python's continue still advances the iterator); then desugar to
        # the shared while form and let visit_While rewrite BREAK, which
        # must guard the bump (python's break leaves the loop variable at
        # its break-time value — `for i in range(10): if i == 3: break`
        # ends with i == 3, not 9)
        a = node.iter.args
        if len(a) == 3 and not (
            isinstance(a[2], ast.Constant) and isinstance(a[2].value, int)
            and a[2].value > 0
        ):
            return node  # dynamic/negative step: python semantics (checked
            # BEFORE any rewrite so a bail leaves the body untouched)
        prelude = []
        if has_c:
            self._counter[0] += 1
            cnt = f"_pt_cnt_bc{self._counter[0]}"
            body_c = self._rewrite(list(node.body), None, cnt)
            node.body = [_assign_const(cnt, False)] + body_c
            prelude.append(_assign_const(cnt, False))  # XLA carry init
        self._counter[0] += 1
        for_prelude, loop = _range_for_to_while(node, f"bc{self._counter[0]}")
        prelude = for_prelude + prelude
        res = self.visit_While(loop) if has_b else loop
        res = res if isinstance(res, list) else [res]
        out = prelude + res
        for x in out:
            ast.copy_location(x, node)
            ast.fix_missing_locations(x)
        return out

    def _rewrite(self, stmts, brk, cnt):
        """Flag-selective pass: a None flag leaves that statement kind in
        place for a later pass (visit_For rewrites continue before the
        for→while desugar so the loop-variable bump stays un-guarded, then
        visit_While rewrites break so the bump IS guarded)."""
        flags = [f for f in (brk, cnt) if f]
        out = []
        for i, s in enumerate(stmts):
            rest = stmts[i + 1:]
            if isinstance(s, ast.Break):
                if brk is None:
                    out.append(s)
                    continue
                out.append(_assign_const(brk, True))
                return out
            if isinstance(s, ast.Continue):
                if cnt is None:
                    out.append(s)
                    continue
                out.append(_assign_const(cnt, True))
                return out
            if isinstance(s, ast.If):
                hb, hc = _scan_bc([s])
                if (hb and brk) or (hc and cnt):
                    s.body = self._rewrite(s.body, brk, cnt) or [ast.Pass()]
                    s.orelse = self._rewrite(s.orelse, brk, cnt)
                    out.append(s)
                    if rest:
                        out.append(_flag_guard(
                            flags, self._rewrite(rest, brk, cnt)
                        ))
                    return out
            out.append(s)
        return out


def _walk_same_scope(node):
    """ast.walk that does NOT descend into nested function/class scopes
    (their locals are not this scope's assignments) — including when the
    root itself is one (a nested def appearing as a body statement)."""
    yield node
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda, ast.ClassDef)):
        return
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        yield from _walk_same_scope(child)


def _assigned_names(nodes):
    """Names bound by assignment/augassign within nodes (current scope)."""
    out = []
    for node in nodes:
        for sub in _walk_same_scope(node):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    out.extend(_target_names(t))
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                out.extend(_target_names(sub.target))
    seen = []
    for n in out:
        if n not in seen:
            seen.append(n)
    return seen


def _prelude(names):
    """`try: n = n / except NameError: n = _pt_jst.UNDEF` per name — the
    UndefinedVar seeding (variable_trans_func.py) so branch/loop closures
    can always read and return every merged name."""
    stmts = []
    for n in names:
        stmts.append(ast.Try(
            body=[ast.Assign(
                targets=[ast.Name(id=n, ctx=ast.Store())],
                value=ast.Name(id=n, ctx=ast.Load()),
            )],
            handlers=[ast.ExceptHandler(
                type=ast.Name(id="NameError", ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[ast.Name(id=n, ctx=ast.Store())],
                    value=ast.Attribute(
                        value=ast.Name(id="_pt_jst", ctx=ast.Load()),
                        attr="UNDEF", ctx=ast.Load(),
                    ),
                )],
            )],
            orelse=[], finalbody=[],
        ))
    return stmts


def _target_names(t):
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out = []
        for e in t.elts:
            out.extend(_target_names(e))
        return out
    return []


def _loaded_names(node):
    return {
        sub.id for sub in ast.walk(node)
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
    }


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._n = 0

    def _uid(self):
        self._n += 1
        return self._n

    # -- if/else ------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        uid = self._uid()

        def ends_in_return(body):
            return bool(body) and isinstance(body[-1], ast.Return)

        has_return = any(
            isinstance(s, ast.Return)
            for b in (node.body, node.orelse) for stmt in b
            for s in _walk_same_scope(stmt)
        )
        if has_return:
            # supported: both branches ARE a single return (the common
            # `if c: return a` / `else: return b` tail); otherwise leave
            # untouched (plain python — fails only on traced preds)
            if (
                len(node.body) == 1 and ends_in_return(node.body)
                and len(node.orelse) == 1 and ends_in_return(node.orelse)
            ):
                t = ast.Lambda(
                    args=_no_args(), body=node.body[0].value or
                    ast.Constant(None),
                )
                f = ast.Lambda(
                    args=_no_args(), body=node.orelse[0].value or
                    ast.Constant(None),
                )
                call = _call("convert_ifelse", [node.test, t, f])
                return ast.copy_location(ast.Return(value=call), node)
            return node

        modified = _assigned_names(node.body + node.orelse)
        if not modified:
            return node  # side-effect-only branches: leave to tracing

        tname, fname = f"_pt_true_{uid}", f"_pt_false_{uid}"
        ret = ast.Return(
            value=ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in modified],
                ctx=ast.Load(),
            ) if len(modified) > 1 else ast.Name(id=modified[0],
                                                ctx=ast.Load())
        )
        # the modified names come in as PARAMETERS (seeded from the outer
        # scope) so branch bodies can read-then-write them
        branch_args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in modified],
            kwonlyargs=[], kw_defaults=[], defaults=[],
        )
        t_def = ast.FunctionDef(
            name=tname, args=branch_args,
            body=(node.body or [ast.Pass()]) + [ret],
            decorator_list=[], type_params=[],
        )
        f_def = ast.FunctionDef(
            name=fname, args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=n) for n in modified],
                kwonlyargs=[], kw_defaults=[], defaults=[],
            ),
            body=(node.orelse or [ast.Pass()]) + [ret],
            decorator_list=[], type_params=[],
        )
        assign = ast.Assign(
            targets=[
                ast.Tuple(
                    elts=[ast.Name(id=n, ctx=ast.Store()) for n in modified],
                    ctx=ast.Store(),
                ) if len(modified) > 1 else ast.Name(id=modified[0],
                                                     ctx=ast.Store())
            ],
            value=_call(
                "convert_ifelse",
                [node.test, ast.Name(id=tname, ctx=ast.Load()),
                 ast.Name(id=fname, ctx=ast.Load()),
                 ast.Tuple(
                     elts=[ast.Name(id=n, ctx=ast.Load()) for n in modified],
                     ctx=ast.Load(),
                 )],
            ),
        )
        return [
            ast.copy_location(x, node)
            for x in _prelude(modified) + [t_def, f_def, assign]
        ]

    # -- while --------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        # same-scope walk: the branch closures generated by visit_If contain
        # `return` statements that belong to THEIR scope, not the loop's
        if node.orelse or any(
            isinstance(s, (ast.Break, ast.Continue, ast.Return))
            for stmt in node.body for s in _walk_same_scope(stmt)
        ):
            return node  # unsupported: keep python semantics
        uid = self._uid()
        # the carry is EVERY name the body assigns — a write-only var's
        # final value must survive the loop for post-loop readers
        carry = _assigned_names(node.body)
        if not carry:
            return node

        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in carry],
            kwonlyargs=[], kw_defaults=[], defaults=[],
        )
        cname, bname = f"_pt_wcond_{uid}", f"_pt_wbody_{uid}"
        c_def = ast.FunctionDef(
            name=cname, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[],
            type_params=[],
        )
        ret = ast.Return(
            value=ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in carry],
                ctx=ast.Load(),
            )
        )
        b_def = ast.FunctionDef(
            name=bname, args=args, body=node.body + [ret],
            decorator_list=[], type_params=[],
        )
        assign = ast.Assign(
            targets=[
                ast.Tuple(
                    elts=[ast.Name(id=n, ctx=ast.Store()) for n in carry],
                    ctx=ast.Store(),
                ) if len(carry) > 1 else ast.Name(id=carry[0],
                                                 ctx=ast.Store())
            ],
            value=_call(
                "convert_while_loop",
                [ast.Name(id=cname, ctx=ast.Load()),
                 ast.Name(id=bname, ctx=ast.Load()),
                 ast.Tuple(
                     elts=[ast.Name(id=n, ctx=ast.Load()) for n in carry],
                     ctx=ast.Load(),
                 )],
            ),
        )
        return [
            ast.copy_location(x, node)
            for x in _prelude(carry) + [c_def, b_def, assign]
        ]

    # -- for over range -----------------------------------------------------
    def visit_For(self, node):
        """``for i in range(...)`` desugars to the while form, which then
        lowers through visit_While (loop_transformer.py's for→while). The
        desugaring itself is shared with the break/continue phase
        (_range_for_to_while) so both phases agree on loop-variable
        semantics."""
        self.generic_visit(node)
        if (
            node.orelse
            or not _is_range_for(node)
            or any(
                isinstance(s, (ast.Break, ast.Continue, ast.Return))
                for stmt in node.body for s in _walk_same_scope(stmt)
            )
        ):
            return node
        uid = self._uid()
        lowered = _range_for_to_while(node, str(uid))
        if lowered is None:
            return node  # negative/dynamic step: keep python semantics
        prelude, loop = lowered
        res = self.visit_While(loop)
        res = res if isinstance(res, list) else [res]
        return [ast.copy_location(x, node) for x in prelude + res]

    # -- print / assert / casts ---------------------------------------------
    def visit_Call(self, node):
        self.generic_visit(node)
        if isinstance(node.func, ast.Name):
            if node.func.id == "print":
                return ast.copy_location(ast.Call(
                    func=ast.Attribute(
                        value=ast.Name(id="_pt_jst", ctx=ast.Load()),
                        attr="convert_print", ctx=ast.Load(),
                    ),
                    args=node.args, keywords=node.keywords,
                ), node)
            if (
                node.func.id in ("int", "float", "bool", "len")
                and len(node.args) == 1 and not node.keywords
                and not isinstance(node.args[0], ast.Starred)
            ):
                return ast.copy_location(
                    _call("convert_cast",
                          [ast.Constant(node.func.id), node.args[0]]),
                    node,
                )
        return node

    def visit_Assert(self, node):
        self.generic_visit(node)
        args = [node.test] + ([node.msg] if node.msg is not None else [])
        return ast.copy_location(
            ast.Expr(value=_call("convert_assert", args)), node
        )

    # -- and/or/not ---------------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        out = node.values[-1]
        for v in reversed(node.values[:-1]):
            out = _call(
                fn,
                [ast.Lambda(args=_no_args(), body=v),
                 ast.Lambda(args=_no_args(), body=out)],
            )
        return ast.copy_location(out, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(
                _call("convert_logical_not", [node.operand]), node
            )
        return node


def _call(name, args):
    return ast.Call(
        func=ast.Attribute(
            value=ast.Name(id="_pt_jst", ctx=ast.Load()),
            attr=name, ctx=ast.Load(),
        ),
        args=args, keywords=[],
    )


def _no_args():
    return ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                         kw_defaults=[], defaults=[])


_no_args_def = _no_args


def convert_to_static(fn):
    """Rewrite ``fn``'s control flow (program_translator.py role).

    Returns the transformed function, or ``fn`` unchanged when the
    source is unavailable or the transform does not apply.
    """
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
        fdef = tree.body[0]
        fdef.decorator_list = []  # the decorator would recurse
        # phase order matters: returns become flag+break first, then
        # break/continue become flag+guard form, then control flow lowers
        # to the runtime converters (the reference stacks its transformers
        # the same way, program_translator.py transform pipeline)
        tree = _ReturnTransformer().visit(tree)
        tree = _BreakContinueTransformer().visit(tree)
        new = _ControlFlowTransformer().visit(tree)
        ast.fix_missing_locations(new)
        code = compile(new, f"<dygraph_to_static:{fn.__qualname__}>",
                       "exec")
        import sys

        this = sys.modules[__name__]
        glb = dict(fn.__globals__)
        glb["_pt_jst"] = this
        # freevars of the original become globals of the rebuilt module-
        # level def: seed them with the current cell contents (snapshot
        # semantics — the reference's ProgramTranslator captures the
        # same way)
        for name, cell in zip(fn.__code__.co_freevars,
                              fn.__closure__ or ()):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass  # empty cell (e.g. recursive self-reference)
        loc = {}
        exec(code, glb, loc)  # noqa: S102 — AST we just built
        transformed = loc[fdef.name]
        functools.update_wrapper(transformed, fn)
        transformed.__wrapped_original__ = fn
        return transformed
    except (OSError, TypeError, SyntaxError):
        return fn
