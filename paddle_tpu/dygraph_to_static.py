"""Dygraph-to-static AST transforms (value-dependent control flow).

Reference parity: python/paddle/fluid/dygraph/dygraph_to_static/ — the
AST transformer stack (ifelse_transformer.py, loop_transformer.py,
logical_transformer.py, program_translator.py). The reference rewrites
Python `if`/`while`/`and`/`or` over Variables into conditional_block /
while ops; here they rewrite into runtime converter calls that dispatch
on tracedness:

- concrete (eager) values  → plain Python control flow, unchanged
  semantics;
- traced values (inside a compiled step / to_static trace) →
  lax.cond / lax.while_loop / jnp.logical_*, which is how XLA wants
  data-dependent control flow expressed.

Supported v1 surface (unsupported shapes are left untouched and only
fail if the predicate is actually traced, with a clear message):

- ``if``/``elif``/``else`` whose branches assign local names (the
  modified names become the merged outputs) or where both branches end
  in ``return``;
- ``while`` loops whose body assigns local names (the loop carry);
- ``and`` / ``or`` / ``not`` inside the transformed function.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .framework.tensor import Tensor

__all__ = [
    "convert_ifelse",
    "convert_while_loop",
    "convert_logical_and",
    "convert_logical_or",
    "convert_logical_not",
    "convert_to_static",
    "UNDEF",
]


class _Undefined:
    """Sentinel for names not yet bound when a transformed control-flow
    region starts (the reference's UndefinedVar,
    dygraph_to_static/variable_trans_func.py)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<paddle_tpu UNDEF>"

    def __bool__(self):
        raise NameError(
            "variable is used before assignment inside transformed "
            "control flow"
        )


UNDEF = _Undefined()


# ---------------------------------------------------------------------------
# runtime converters (dygraph_to_static/convert_operators.py equivalents)
# ---------------------------------------------------------------------------


def _arr(v):
    return v._array if isinstance(v, Tensor) else v


def _is_traced(v):
    return isinstance(_arr(v), jax.core.Tracer)


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        _arr, tree, is_leaf=lambda x: isinstance(x, Tensor)
    )


def _rewrap_like(arrays, template):
    flat_t, treedef = jax.tree_util.tree_flatten(
        template, is_leaf=lambda x: isinstance(x, Tensor)
    )
    flat_a = jax.tree_util.tree_leaves(arrays)
    out = [
        Tensor._from_array(a) if isinstance(t, Tensor) else a
        for a, t in zip(flat_a, flat_t)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def convert_ifelse(pred, true_fn, false_fn):
    """ifelse_transformer target: branch on a maybe-traced predicate."""
    if not _is_traced(pred):
        p = _arr(pred)
        taken = bool(np.asarray(p)) if hasattr(p, "dtype") else bool(p)
        return true_fn() if taken else false_fn()
    p = jnp.reshape(_arr(pred), ()).astype(bool)

    # trace both branches; unify pytrees of Tensors/arrays. The first
    # trace of true_fn doubles as the Tensor-vs-array structure template
    # (no extra call — branches may be expensive to trace).
    sample = [None]

    def mk(fn, capture=False):
        def f(_):
            out = fn()
            if capture:
                sample[0] = out
            return _unwrap_tree(out)
        return f

    out = lax.cond(p, mk(true_fn, capture=True), mk(false_fn), None)
    return _rewrap_like(out, sample[0])


def convert_while_loop(cond_fn, body_fn, loop_vars):
    """loop_transformer target: while over a maybe-traced condition.

    Note the XLA contract: a traced while_loop is not reverse-
    differentiable (use the scan construct for trainable loops).
    """
    if any(v is UNDEF for v in loop_vars):
        # a name assigned inside the loop but unbound before it: fine in
        # the python path (it binds on the first iteration), impossible
        # as an XLA loop carry (fixed structure)
        if any(_is_traced(v) for v in loop_vars if v is not UNDEF):
            raise NameError(
                "transformed while loop: a carried variable is not "
                "initialized before the loop; XLA loop carries need an "
                "initial value — assign it before the while"
            )
        env = list(loop_vars)
        while bool(np.asarray(_arr(cond_fn(*env)))):
            out = body_fn(*env)
            env = list(out) if isinstance(out, tuple) else [out]
        return tuple(env) if len(env) > 1 else env[0]

    first = cond_fn(*loop_vars)
    if not _is_traced(first) and not any(_is_traced(v) for v in loop_vars):
        vars_ = tuple(loop_vars)
        while bool(np.asarray(_arr(cond_fn(*vars_)))):
            out = body_fn(*vars_)
            vars_ = tuple(out) if isinstance(out, tuple) else (out,)
        return vars_ if len(vars_) > 1 else vars_[0]

    template = tuple(loop_vars)
    init = tuple(_arr(v) for v in loop_vars)

    def cond(c):
        vs = _rewrap_like(c, template)
        return jnp.reshape(_arr(cond_fn(*vs)), ()).astype(bool)

    def body(c):
        vs = _rewrap_like(c, template)
        out = body_fn(*vs)
        out = out if isinstance(out, tuple) else (out,)
        return tuple(_arr(v) for v in out)

    final = lax.while_loop(cond, body, init)
    out = _rewrap_like(final, template)
    return out if len(template) > 1 else out[0]


def convert_logical_and(x_fn, y_fn):
    x = x_fn()
    if not _is_traced(x):
        xa = _arr(x)
        if hasattr(xa, "dtype") and np.asarray(xa).size == 1:
            if not bool(np.asarray(xa)):
                return x  # python short-circuit semantics
            return y_fn()
        if not hasattr(xa, "dtype"):
            return x and y_fn()
    y = y_fn()
    return Tensor._from_array(
        jnp.logical_and(
            jnp.asarray(_arr(x)).astype(bool),
            jnp.asarray(_arr(y)).astype(bool),
        )
    )


def convert_logical_or(x_fn, y_fn):
    x = x_fn()
    if not _is_traced(x):
        xa = _arr(x)
        if hasattr(xa, "dtype") and np.asarray(xa).size == 1:
            if bool(np.asarray(xa)):
                return x
            return y_fn()
        if not hasattr(xa, "dtype"):
            return x or y_fn()
    y = y_fn()
    return Tensor._from_array(
        jnp.logical_or(
            jnp.asarray(_arr(x)).astype(bool),
            jnp.asarray(_arr(y)).astype(bool),
        )
    )


def convert_logical_not(x):
    if not _is_traced(x) and not hasattr(_arr(x), "dtype"):
        return not x
    return Tensor._from_array(jnp.logical_not(
        jnp.asarray(_arr(x)).astype(bool)
    ))


# ---------------------------------------------------------------------------
# AST transformer (ifelse_transformer.py / loop_transformer.py)
# ---------------------------------------------------------------------------


def _walk_same_scope(node):
    """ast.walk that does NOT descend into nested function/class scopes
    (their locals are not this scope's assignments)."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        yield from _walk_same_scope(child)


def _assigned_names(nodes):
    """Names bound by assignment/augassign within nodes (current scope)."""
    out = []
    for node in nodes:
        for sub in _walk_same_scope(node):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    out.extend(_target_names(t))
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                out.extend(_target_names(sub.target))
    seen = []
    for n in out:
        if n not in seen:
            seen.append(n)
    return seen


def _prelude(names):
    """`try: n = n / except NameError: n = _pt_jst.UNDEF` per name — the
    UndefinedVar seeding (variable_trans_func.py) so branch/loop closures
    can always read and return every merged name."""
    stmts = []
    for n in names:
        stmts.append(ast.Try(
            body=[ast.Assign(
                targets=[ast.Name(id=n, ctx=ast.Store())],
                value=ast.Name(id=n, ctx=ast.Load()),
            )],
            handlers=[ast.ExceptHandler(
                type=ast.Name(id="NameError", ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[ast.Name(id=n, ctx=ast.Store())],
                    value=ast.Attribute(
                        value=ast.Name(id="_pt_jst", ctx=ast.Load()),
                        attr="UNDEF", ctx=ast.Load(),
                    ),
                )],
            )],
            orelse=[], finalbody=[],
        ))
    return stmts


def _target_names(t):
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out = []
        for e in t.elts:
            out.extend(_target_names(e))
        return out
    return []


def _loaded_names(node):
    return {
        sub.id for sub in ast.walk(node)
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
    }


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._n = 0

    def _uid(self):
        self._n += 1
        return self._n

    # -- if/else ------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        uid = self._uid()

        def ends_in_return(body):
            return bool(body) and isinstance(body[-1], ast.Return)

        has_return = any(
            isinstance(s, ast.Return)
            for b in (node.body, node.orelse) for stmt in b
            for s in ast.walk(stmt)
        )
        if has_return:
            # supported: both branches ARE a single return (the common
            # `if c: return a` / `else: return b` tail); otherwise leave
            # untouched (plain python — fails only on traced preds)
            if (
                len(node.body) == 1 and ends_in_return(node.body)
                and len(node.orelse) == 1 and ends_in_return(node.orelse)
            ):
                t = ast.Lambda(
                    args=_no_args(), body=node.body[0].value or
                    ast.Constant(None),
                )
                f = ast.Lambda(
                    args=_no_args(), body=node.orelse[0].value or
                    ast.Constant(None),
                )
                call = _call("convert_ifelse", [node.test, t, f])
                return ast.copy_location(ast.Return(value=call), node)
            return node

        modified = _assigned_names(node.body + node.orelse)
        if not modified:
            return node  # side-effect-only branches: leave to tracing

        tname, fname = f"_pt_true_{uid}", f"_pt_false_{uid}"
        ret = ast.Return(
            value=ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in modified],
                ctx=ast.Load(),
            ) if len(modified) > 1 else ast.Name(id=modified[0],
                                                ctx=ast.Load())
        )
        t_def = ast.FunctionDef(
            name=tname, args=_no_args_def(),
            body=(node.body or [ast.Pass()]) + [ret],
            decorator_list=[], type_params=[],
        )
        f_def = ast.FunctionDef(
            name=fname, args=_no_args_def(),
            body=(node.orelse or [ast.Pass()]) + [ret],
            decorator_list=[], type_params=[],
        )
        assign = ast.Assign(
            targets=[
                ast.Tuple(
                    elts=[ast.Name(id=n, ctx=ast.Store()) for n in modified],
                    ctx=ast.Store(),
                ) if len(modified) > 1 else ast.Name(id=modified[0],
                                                     ctx=ast.Store())
            ],
            value=_call(
                "convert_ifelse",
                [node.test, ast.Name(id=tname, ctx=ast.Load()),
                 ast.Name(id=fname, ctx=ast.Load())],
            ),
        )
        return [
            ast.copy_location(x, node)
            for x in _prelude(modified) + [t_def, f_def, assign]
        ]

    # -- while --------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or any(
            isinstance(s, (ast.Break, ast.Continue, ast.Return))
            for stmt in node.body for s in ast.walk(stmt)
        ):
            return node  # unsupported: keep python semantics
        uid = self._uid()
        # the carry is EVERY name the body assigns — a write-only var's
        # final value must survive the loop for post-loop readers
        carry = _assigned_names(node.body)
        if not carry:
            return node

        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in carry],
            kwonlyargs=[], kw_defaults=[], defaults=[],
        )
        cname, bname = f"_pt_wcond_{uid}", f"_pt_wbody_{uid}"
        c_def = ast.FunctionDef(
            name=cname, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[],
            type_params=[],
        )
        ret = ast.Return(
            value=ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in carry],
                ctx=ast.Load(),
            )
        )
        b_def = ast.FunctionDef(
            name=bname, args=args, body=node.body + [ret],
            decorator_list=[], type_params=[],
        )
        assign = ast.Assign(
            targets=[
                ast.Tuple(
                    elts=[ast.Name(id=n, ctx=ast.Store()) for n in carry],
                    ctx=ast.Store(),
                ) if len(carry) > 1 else ast.Name(id=carry[0],
                                                 ctx=ast.Store())
            ],
            value=_call(
                "convert_while_loop",
                [ast.Name(id=cname, ctx=ast.Load()),
                 ast.Name(id=bname, ctx=ast.Load()),
                 ast.Tuple(
                     elts=[ast.Name(id=n, ctx=ast.Load()) for n in carry],
                     ctx=ast.Load(),
                 )],
            ),
        )
        return [
            ast.copy_location(x, node)
            for x in _prelude(carry) + [c_def, b_def, assign]
        ]

    # -- for over range -----------------------------------------------------
    def visit_For(self, node):
        """``for i in range(...)`` desugars to the while form, which then
        lowers through visit_While (loop_transformer.py's for→while)."""
        self.generic_visit(node)
        if (
            node.orelse
            or not isinstance(node.target, ast.Name)
            or not isinstance(node.iter, ast.Call)
            or not isinstance(node.iter.func, ast.Name)
            or node.iter.func.id != "range"
            or node.iter.keywords
            or not 1 <= len(node.iter.args) <= 3
            or any(
                isinstance(s, (ast.Break, ast.Continue, ast.Return))
                for stmt in node.body for s in ast.walk(stmt)
            )
        ):
            return node
        uid = self._uid()
        args = node.iter.args
        start = args[0] if len(args) >= 2 else ast.Constant(0)
        stop = args[1] if len(args) >= 2 else args[0]
        step = args[2] if len(args) == 3 else ast.Constant(1)
        if len(args) == 3 and not (
            isinstance(step, ast.Constant) and isinstance(step.value, int)
            and step.value > 0
        ):
            return node  # negative/dynamic step: keep python semantics
        it = f"_pt_for_{uid}"
        stop_name = f"_pt_stop_{uid}"
        init = ast.Assign(
            targets=[ast.Name(id=it, ctx=ast.Store())], value=start
        )
        # snapshot the bound: python evaluates range() args exactly once,
        # so a body that mutates the bound variable must not change the
        # trip count
        init_stop = ast.Assign(
            targets=[ast.Name(id=stop_name, ctx=ast.Store())], value=stop
        )
        stop = ast.Name(id=stop_name, ctx=ast.Load())
        # pre-bind the loop target ONLY if currently unbound (an empty
        # range must not clobber a prior value) — it then is a
        # well-defined XLA loop carry
        pre_bind = ast.Try(
            body=[ast.Assign(
                targets=[ast.Name(id=node.target.id, ctx=ast.Store())],
                value=ast.Name(id=node.target.id, ctx=ast.Load()),
            )],
            handlers=[ast.ExceptHandler(
                type=ast.Name(id="NameError", ctx=ast.Load()), name=None,
                body=[ast.Assign(
                    targets=[ast.Name(id=node.target.id, ctx=ast.Store())],
                    value=ast.Name(id=it, ctx=ast.Load()),
                )],
            )],
            orelse=[], finalbody=[],
        )
        test = ast.Compare(
            left=ast.Name(id=it, ctx=ast.Load()), ops=[ast.Lt()],
            comparators=[stop],
        )
        bind = ast.Assign(
            targets=[node.target], value=ast.Name(id=it, ctx=ast.Load())
        )
        bump = ast.AugAssign(
            target=ast.Name(id=it, ctx=ast.Store()), op=ast.Add(),
            value=step,
        )
        loop = ast.While(test=test, body=[bind] + node.body + [bump],
                         orelse=[])
        out = [ast.copy_location(x, node)
               for x in (init, init_stop, pre_bind, loop)]
        lowered = self.visit_While(out[3])
        lowered = lowered if isinstance(lowered, list) else [lowered]
        return out[:3] + [
            ast.copy_location(x, node) for x in lowered
        ]

    # -- and/or/not ---------------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        out = node.values[-1]
        for v in reversed(node.values[:-1]):
            out = _call(
                fn,
                [ast.Lambda(args=_no_args(), body=v),
                 ast.Lambda(args=_no_args(), body=out)],
            )
        return ast.copy_location(out, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(
                _call("convert_logical_not", [node.operand]), node
            )
        return node


def _call(name, args):
    return ast.Call(
        func=ast.Attribute(
            value=ast.Name(id="_pt_jst", ctx=ast.Load()),
            attr=name, ctx=ast.Load(),
        ),
        args=args, keywords=[],
    )


def _no_args():
    return ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                         kw_defaults=[], defaults=[])


_no_args_def = _no_args


def convert_to_static(fn):
    """Rewrite ``fn``'s control flow (program_translator.py role).

    Returns the transformed function, or ``fn`` unchanged when the
    source is unavailable or the transform does not apply.
    """
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
        fdef = tree.body[0]
        fdef.decorator_list = []  # the decorator would recurse
        new = _ControlFlowTransformer().visit(tree)
        ast.fix_missing_locations(new)
        code = compile(new, f"<dygraph_to_static:{fn.__qualname__}>",
                       "exec")
        import sys

        this = sys.modules[__name__]
        glb = dict(fn.__globals__)
        glb["_pt_jst"] = this
        # freevars of the original become globals of the rebuilt module-
        # level def: seed them with the current cell contents (snapshot
        # semantics — the reference's ProgramTranslator captures the
        # same way)
        for name, cell in zip(fn.__code__.co_freevars,
                              fn.__closure__ or ()):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass  # empty cell (e.g. recursive self-reference)
        loc = {}
        exec(code, glb, loc)  # noqa: S102 — AST we just built
        transformed = loc[fdef.name]
        functools.update_wrapper(transformed, fn)
        transformed.__wrapped_original__ = fn
        return transformed
    except (OSError, TypeError, SyntaxError):
        return fn
