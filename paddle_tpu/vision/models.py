"""paddle.vision.models namespace — re-export the model zoo."""
from ..models import (  # noqa: F401
    LeNet,
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
)

__all__ = [
    "LeNet", "ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
    "resnet152",
]
