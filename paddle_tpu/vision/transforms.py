"""Image transforms.

Reference parity: incubate/hapi/vision/transforms/ (Compose, Resize,
Normalize, RandomCrop, RandomHorizontalFlip, ToTensor, ...). Operates on
numpy CHW float arrays (the dataset convention here) — cheap host-side
preprocessing; heavy augmentation belongs in the input pipeline workers.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "Compose", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "ToTensor",
    "Pad", "BrightnessTransform",
]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(img, self.order)


class ToTensor:
    """HWC uint8 -> CHW float32 in [0,1] (passes through CHW float)."""

    def __call__(self, img):
        img = np.asarray(img)
        if img.dtype == np.uint8:
            img = img.astype("float32") / 255.0
            if img.ndim == 3 and img.shape[-1] in (1, 3, 4):
                img = np.transpose(img, (2, 0, 1))
        return img.astype("float32")


def _resize_chw(img, h, w):
    c, ih, iw = img.shape
    yi = (np.arange(h) * (ih / h)).astype(np.int64).clip(0, ih - 1)
    xi = (np.arange(w) * (iw / w)).astype(np.int64).clip(0, iw - 1)
    return img[:, yi][:, :, xi]


class Resize:
    def __init__(self, size, interpolation="nearest"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        return _resize_chw(np.asarray(img), *self.size)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        c, h, w = img.shape
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return img[:, i : i + th, j : j + tw]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        if self.padding:
            img = np.pad(
                img,
                ((0, 0), (self.padding,) * 2, (self.padding,) * 2),
                mode="constant",
            )
        c, h, w = img.shape
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[:, i : i + th, j : j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return img[:, :, ::-1].copy()
        return img


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return img[:, ::-1, :].copy()
        return img


class Pad:
    def __init__(self, padding, fill=0):
        self.padding = padding
        self.fill = fill

    def __call__(self, img):
        p = self.padding
        return np.pad(
            img, ((0, 0), (p, p), (p, p)), constant_values=self.fill
        )


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return img
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return (img * alpha).astype(img.dtype)
