"""Built-in datasets.

Reference parity: python/paddle/dataset/ (mnist.py, cifar.py fetchers) and
incubate/hapi datasets. This environment has zero network egress, so each
dataset loads from a local file when present (same on-disk formats as the
reference's cache: idx-gzip for MNIST, pickled batches for CIFAR) and
otherwise generates a deterministic synthetic sample set with the same
shapes/dtypes/label-space — keeping every book-test equivalent runnable
offline. ``backend`` follows the data home convention
(~/.cache/paddle_tpu/dataset).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "VOC2012"]

from ..utils.data_home import DATA_HOME, warn_synthetic as _warn_synthetic


class _SyntheticMixin:
    """Deterministic stand-in data when the real files are absent.

    The substitution is LOUD: a warning names the dataset and what to do
    to get real data, and ``self.synthetic`` is set so tests/metrics can
    refuse to treat noise-trained numbers as real-data results."""

    def _synthesize(self, n, image_shape, num_classes, seed):
        _warn_synthetic(self)
        rng = np.random.RandomState(seed)
        # class patterns come from a split-independent seed so train and
        # test share the same class structure (only noise/labels differ)
        import zlib

        pattern_rng = np.random.RandomState(
            zlib.crc32(type(self).__name__.encode()) % 2**31
        )
        bases = [
            pattern_rng.rand(*image_shape).astype("float32")
            for _ in range(num_classes)
        ]
        labels = rng.randint(0, num_classes, n).astype("int64")
        images = np.zeros((n,) + image_shape, np.float32)
        for c in range(num_classes):
            images[labels == c] = bases[c][None] * 0.8
        images += rng.rand(n, *image_shape).astype("float32") * 0.2
        self.synthetic = True
        return images, labels


class MNIST(_SyntheticMixin, Dataset):
    """paddle.vision.datasets.MNIST (dataset/mnist.py idx format)."""

    IMAGE_SHAPE = (1, 28, 28)
    NUM_CLASSES = 10
    _PREFIX = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.synthetic = False
        split = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(
            DATA_HOME, self._PREFIX, f"{split}-images-idx3-ubyte.gz"
        )
        label_path = label_path or os.path.join(
            DATA_HOME, self._PREFIX, f"{split}-labels-idx1-ubyte.gz"
        )
        if os.path.exists(image_path) and os.path.exists(label_path):
            self.images = self._read_idx_images(image_path)
            self.labels = self._read_idx_labels(label_path)
        else:
            n = 2048 if mode == "train" else 512
            self.images, self.labels = self._synthesize(
                n, self.IMAGE_SHAPE, self.NUM_CLASSES,
                seed=42 if mode == "train" else 43,
            )

    @staticmethod
    def _read_idx_images(path):
        with gzip.open(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), np.uint8).reshape(n, 1, rows, cols)
        return (data.astype("float32") / 255.0 - 0.5) / 0.5

    @staticmethod
    def _read_idx_labels(path):
        with gzip.open(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype("int64")

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    _PREFIX = "fashion-mnist"


class Cifar10(_SyntheticMixin, Dataset):
    """paddle.vision.datasets.Cifar10 (dataset/cifar.py pickled batches)."""

    IMAGE_SHAPE = (3, 32, 32)
    NUM_CLASSES = 10
    _ARCHIVE = "cifar-10-python.tar.gz"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.synthetic = False
        data_file = data_file or os.path.join(DATA_HOME, self._ARCHIVE)
        if os.path.exists(data_file):
            self.images, self.labels = self._read_archive(data_file, mode)
        else:
            n = 2048 if mode == "train" else 512
            self.images, self.labels = self._synthesize(
                n, self.IMAGE_SHAPE, self.NUM_CLASSES,
                seed=44 if mode == "train" else 45,
            )

    def _read_archive(self, path, mode):
        images, labels = [], []
        want = "data_batch" if mode == "train" else "test_batch"
        with tarfile.open(path) as tar:
            for member in tar.getmembers():
                if want in member.name:
                    d = pickle.load(tar.extractfile(member), encoding="bytes")
                    images.append(d[b"data"])
                    labels.extend(d.get(b"labels", d.get(b"fine_labels")))
        images = np.concatenate(images).reshape(-1, 3, 32, 32)
        images = (images.astype("float32") / 255.0 - 0.5) / 0.5
        return images, np.asarray(labels, "int64")

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100
    _ARCHIVE = "cifar-100-python.tar.gz"


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (dataset/voc2012.py): samples are
    (image HWC uint8, label mask HW uint8), read from the VOCtrainval
    tar when present (ImageSets/Segmentation/{set}.txt naming JPEG +
    SegmentationClass pairs), else loud synthetic blobs whose mask
    matches the painted class regions. Modes: train -> trainval list,
    test -> train list, val -> val list (voc2012.py:68-85 mapping)."""

    N_CLASSES = 21

    def __init__(self, data_file=None, mode="train", image_size=64):
        self.synthetic = False
        data_file = data_file or os.path.join(
            DATA_HOME, "voc2012", "VOCtrainval_11-May-2012.tar")
        sub = {"train": "trainval", "test": "train", "val": "val"}[mode]
        if os.path.exists(data_file):
            self._load_tar(data_file, sub)
        else:
            self._synthesize(mode, image_size)

    def _load_tar(self, path, sub):
        import tarfile

        try:
            from PIL import Image  # noqa: F401 — fail before first access
        except ImportError as e:  # pragma: no cover
            raise RuntimeError(
                "VOC2012 real-data path needs PIL to decode JPEG/PNG"
            ) from e
        voc = "VOCdevkit/VOC2012"
        # index only; decode lazily per __getitem__ — trainval holds ~3k
        # full-resolution pairs (>1.5 GB decoded), far too much to
        # materialize at construction
        self._tar_path = path
        with tarfile.open(path) as tf:
            names = set(m.name for m in tf.getmembers())
            listing = tf.extractfile(
                f"{voc}/ImageSets/Segmentation/{sub}.txt")
            self._members = []
            for line in listing.read().decode().split():
                im = f"{voc}/JPEGImages/{line}.jpg"
                lm = f"{voc}/SegmentationClass/{line}.png"
                if im in names and lm in names:
                    self._members.append((im, lm))
        # one tar handle PER PROCESS: forked DataLoader workers must not
        # share a file descriptor (concurrent seeks corrupt reads)
        self._tars = {}
        self.data = None

    def _decode(self, i):
        import io
        import os
        import tarfile

        from PIL import Image

        tar = self._tars.get(os.getpid())
        if tar is None:
            tar = tarfile.open(self._tar_path)
            self._tars[os.getpid()] = tar
        im, lm = self._members[i]
        img = Image.open(io.BytesIO(tar.extractfile(im).read()))
        lab = Image.open(io.BytesIO(tar.extractfile(lm).read()))
        return np.array(img, np.uint8), np.array(lab, np.uint8)

    def _synthesize(self, mode, size):
        _warn_synthetic(self)
        self.synthetic = True
        rng = np.random.RandomState({"train": 71, "test": 73,
                                     "val": 72}[mode])
        n = {"train": 64, "test": 32, "val": 16}[mode]
        self.data = []
        for _ in range(n):
            img = rng.randint(0, 40, (size, size, 3)).astype(np.uint8)
            mask = np.zeros((size, size), np.uint8)
            for _ in range(rng.randint(1, 4)):  # paint class rectangles
                cls = rng.randint(1, self.N_CLASSES)
                y0, x0 = rng.randint(0, size // 2, 2)
                h, w = rng.randint(size // 8, size // 2, 2)
                mask[y0:y0 + h, x0:x0 + w] = cls
                img[y0:y0 + h, x0:x0 + w] += np.uint8(cls * 10)
            self.data.append((img, mask))

    def __getitem__(self, i):
        if self.data is None:
            return self._decode(i)
        return self.data[i]

    def __len__(self):
        return (len(self._members) if self.data is None
                else len(self.data))
