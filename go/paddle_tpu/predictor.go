// Reference parity: /root/reference/go/paddle/predictor.go — the Go
// Predictor over the C ABI (NewPredictor / input-output introspection /
// SetZeroCopyInput / ZeroCopyRun / GetZeroCopyOutput), retargeted at
// libpaddle_tpu_capi.so. One XLA compile per (model, input shapes); later
// Run() calls dispatch the cached executable.
package paddle_tpu

// #include <stdlib.h>
// extern void* PD_CreatePredictor(const char* model_dir);
// extern void PD_DeletePredictor(void* pred);
// extern int PD_GetInputNum(void* pred);
// extern int PD_GetOutputNum(void* pred);
// extern const char* PD_GetInputName(void* pred, int i);
// extern const char* PD_GetOutputName(void* pred, int i);
// extern int PD_SetInputFloat(void* pred, const char* name,
//                             const float* data, const long long* shape,
//                             int ndim);
// extern int PD_SetInputInt64(void* pred, const char* name,
//                             const long long* data,
//                             const long long* shape, int ndim);
// extern int PD_Run(void* pred);
// extern int PD_GetOutputNdim(void* pred, const char* name);
// extern int PD_GetOutputShape(void* pred, const char* name,
//                              long long* shape_out);
// extern int PD_CopyOutputFloat(void* pred, const char* name, float* buf,
//                               long long numel);
import "C"

import (
	"fmt"
	"runtime"
	"unsafe"
)

// keepAlive pins p for the duration of an in-flight cgo call so the
// SetFinalizer-driven Delete cannot free the C handle concurrently.
func (p *Predictor) keepAlive() { runtime.KeepAlive(p) }

type Predictor struct {
	c unsafe.Pointer
}

// NewPredictor loads a save_inference_model directory and compiles the
// program for the first Run's shapes.
func NewPredictor(config *AnalysisConfig) (*Predictor, error) {
	if err := Init(); err != nil {
		return nil, err
	}
	dir := C.CString(config.ModelDir())
	defer C.free(unsafe.Pointer(dir))
	h := C.PD_CreatePredictor(dir)
	if h == nil {
		return nil, lastError()
	}
	p := &Predictor{c: h}
	runtime.SetFinalizer(p, func(q *Predictor) { q.Delete() })
	return p, nil
}

func DeletePredictor(p *Predictor) { p.Delete() }

func (p *Predictor) Delete() {
	if p.c != nil {
		C.PD_DeletePredictor(p.c)
		p.c = nil
	}
}

func (p *Predictor) GetInputNum() int {
	defer p.keepAlive()
	return int(C.PD_GetInputNum(p.c))
}

func (p *Predictor) GetOutputNum() int {
	defer p.keepAlive()
	return int(C.PD_GetOutputNum(p.c))
}

func (p *Predictor) GetInputName(i int) string {
	defer p.keepAlive()
	return C.GoString(C.PD_GetInputName(p.c, C.int(i)))
}

func (p *Predictor) GetOutputName(i int) string {
	defer p.keepAlive()
	return C.GoString(C.PD_GetOutputName(p.c, C.int(i)))
}

func (p *Predictor) GetInputNames() []string {
	names := make([]string, p.GetInputNum())
	for i := range names {
		names[i] = p.GetInputName(i)
	}
	return names
}

func (p *Predictor) GetOutputNames() []string {
	names := make([]string, p.GetOutputNum())
	for i := range names {
		names[i] = p.GetOutputName(i)
	}
	return names
}

// SetZeroCopyInput stages one named input for the next Run.
func (p *Predictor) SetZeroCopyInput(t *ZeroCopyTensor) error {
	defer p.keepAlive()
	name := C.CString(t.Name)
	defer C.free(unsafe.Pointer(name))
	var shapePtr *C.longlong
	if len(t.Shape) > 0 {
		shapePtr = (*C.longlong)(unsafe.Pointer(&t.Shape[0]))
	}
	var rc C.int
	switch t.Dtype {
	case Float32:
		if int64(len(t.FloatData)) != t.numel() {
			return fmt.Errorf("input %q: %d values for shape %v",
				t.Name, len(t.FloatData), t.Shape)
		}
		var data *C.float
		if len(t.FloatData) > 0 { // zero-numel: valid shape, nil payload
			data = (*C.float)(unsafe.Pointer(&t.FloatData[0]))
		}
		rc = C.PD_SetInputFloat(p.c, name, data, shapePtr,
			C.int(len(t.Shape)))
	case Int64:
		if int64(len(t.Int64Data)) != t.numel() {
			return fmt.Errorf("input %q: %d values for shape %v",
				t.Name, len(t.Int64Data), t.Shape)
		}
		var data *C.longlong
		if len(t.Int64Data) > 0 {
			data = (*C.longlong)(unsafe.Pointer(&t.Int64Data[0]))
		}
		rc = C.PD_SetInputInt64(p.c, name, data, shapePtr,
			C.int(len(t.Shape)))
	default:
		return fmt.Errorf("input %q: unsupported dtype", t.Name)
	}
	if rc != 0 {
		return lastError()
	}
	return nil
}

// ZeroCopyRun executes the compiled program on the staged inputs.
func (p *Predictor) ZeroCopyRun() error {
	defer p.keepAlive()
	if C.PD_Run(p.c) != 0 {
		return lastError()
	}
	return nil
}

// GetZeroCopyOutput fetches a named output (float32) after a Run.
func (p *Predictor) GetZeroCopyOutput(t *ZeroCopyTensor) error {
	defer p.keepAlive()
	name := C.CString(t.Name)
	defer C.free(unsafe.Pointer(name))
	ndim := int(C.PD_GetOutputNdim(p.c, name))
	if ndim < 0 {
		return lastError()
	}
	t.Shape = make([]int64, ndim)
	if ndim > 0 {
		if C.PD_GetOutputShape(p.c, name,
			(*C.longlong)(unsafe.Pointer(&t.Shape[0]))) != 0 {
			return lastError()
		}
	}
	t.Dtype = Float32
	t.FloatData = make([]float32, t.numel())
	var buf *C.float
	if len(t.FloatData) > 0 {
		buf = (*C.float)(unsafe.Pointer(&t.FloatData[0]))
	}
	if C.PD_CopyOutputFloat(p.c, name, buf, C.longlong(t.numel())) != 0 {
		return lastError()
	}
	return nil
}
