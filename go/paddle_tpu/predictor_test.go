//go:build capi

// End-to-end Go client test (reference: go/paddle/*_test patterns).
// Gated behind the `capi` build tag because CI images may lack a Go
// toolchain and the built C library; run it with:
//
//	# export any model via paddle_tpu.static.save_inference_model first
//	CAPI=$(python -c "from paddle_tpu._native.capi import build_capi; print(build_capi())")
//	export CGO_LDFLAGS="-L$(dirname $CAPI) -lpaddle_tpu_capi \
//	  -L$(python3-config --prefix)/lib -lpython3.12"
//	export LD_LIBRARY_PATH=$(dirname $CAPI):$(python3-config --prefix)/lib
//	PADDLE_TPU_GO_MODEL=/tmp/go_model go test -tags capi ./...
package paddle_tpu

import (
	"os"
	"testing"
)

func TestPredictorEndToEnd(t *testing.T) {
	dir := os.Getenv("PADDLE_TPU_GO_MODEL")
	if dir == "" {
		t.Skip("set PADDLE_TPU_GO_MODEL to a save_inference_model dir")
	}
	cfg := NewAnalysisConfig()
	cfg.SetModel(dir)
	pred, err := NewPredictor(cfg)
	if err != nil {
		t.Fatal(err)
	}
	defer pred.Delete()

	if pred.GetInputNum() < 1 || pred.GetOutputNum() < 1 {
		t.Fatalf("io: %d in, %d out", pred.GetInputNum(),
			pred.GetOutputNum())
	}
	in := &ZeroCopyTensor{Name: pred.GetInputName(0)}
	in.Reshape([]int64{2, 4})
	in.SetValue(make([]float32, 8))
	if err := pred.SetZeroCopyInput(in); err != nil {
		t.Fatal(err)
	}
	if err := pred.ZeroCopyRun(); err != nil {
		t.Fatal(err)
	}
	out := &ZeroCopyTensor{Name: pred.GetOutputName(0)}
	if err := pred.GetZeroCopyOutput(out); err != nil {
		t.Fatal(err)
	}
	if len(out.FloatData) == 0 {
		t.Fatal("empty output")
	}
}
