// Reference parity: /root/reference/go/paddle/tensor.go ZeroCopyTensor.
// The TPU C ABI copies at the boundary (host<->device staging makes true
// zero-copy meaningless), so this Tensor is a plain (name, shape, data)
// record with float32/int64 payloads — the two dtypes the reference
// client marshals most.
package paddle_tpu

type DataType int

const (
	Float32 DataType = iota
	Int64
)

// ZeroCopyTensor keeps the reference's type name so call sites port.
type ZeroCopyTensor struct {
	Name      string
	Shape     []int64
	Dtype     DataType
	FloatData []float32
	Int64Data []int64
}

// Reshape sets the tensor shape (reference method).
func (t *ZeroCopyTensor) Reshape(shape []int64) { t.Shape = shape }

// SetValue populates the payload from a typed slice.
func (t *ZeroCopyTensor) SetValue(v interface{}) {
	switch x := v.(type) {
	case []float32:
		t.Dtype = Float32
		t.FloatData = x
	case []int64:
		t.Dtype = Int64
		t.Int64Data = x
	default:
		panic("ZeroCopyTensor.SetValue: want []float32 or []int64")
	}
}

func (t *ZeroCopyTensor) numel() int64 {
	n := int64(1)
	for _, d := range t.Shape {
		n *= d
	}
	return n
}
