// Reference parity: /root/reference/go/paddle/config.go AnalysisConfig.
// The TPU predictor needs only the model directory (save_inference_model
// output); the cudnn/TensorRT/MKLDNN toggles of the reference are
// absorbed by XLA compilation, and the setters are accepted as no-ops so
// reference call sites port unchanged.
package paddle_tpu

// AnalysisConfig mirrors the reference's config surface.
type AnalysisConfig struct {
	modelDir     string
	irOptim      bool
	cpuMathNum   int
	switchBlobs  bool
}

func NewAnalysisConfig() *AnalysisConfig {
	return &AnalysisConfig{irOptim: true}
}

// SetModel points at a save_inference_model directory.
func (c *AnalysisConfig) SetModel(model string, params ...string) {
	c.modelDir = model
}

func (c *AnalysisConfig) ModelDir() string { return c.modelDir }

func (c *AnalysisConfig) SwitchIrOptim(x bool)    { c.irOptim = x }
func (c *AnalysisConfig) IrOptim() bool           { return c.irOptim }
func (c *AnalysisConfig) EnableUseGpu(mb, id int) {} // XLA owns devices
func (c *AnalysisConfig) DisableGpu()             {}
func (c *AnalysisConfig) SetCpuMathLibraryNumThreads(n int) {
	c.cpuMathNum = n
}
func (c *AnalysisConfig) SwitchSpecifyInputNames(bool) {} // always named
func (c *AnalysisConfig) EnableMemoryOptim()           {}
