// Go inference client for paddle_tpu over the C ABI.
//
// Reference parity: /root/reference/go/paddle/common.go — the cgo
// preamble and shared helpers of the reference's Go client, retargeted
// at libpaddle_tpu_capi.so (paddle_tpu/_native/capi.cpp), which embeds
// CPython and drives the XLA-compiled predictor.
//
// Build:
//
//	CAPI=$(python -c "from paddle_tpu._native.capi import build_capi; print(build_capi())")
//	export CGO_LDFLAGS="-L$(dirname $CAPI) -lpaddle_tpu_capi"
//	export LD_LIBRARY_PATH=$(dirname $CAPI):$LD_LIBRARY_PATH
//	go build ./...
//
// PYTHONPATH must reach paddle_tpu at runtime (PD_Init imports it).
package paddle_tpu

// #cgo LDFLAGS: -lpaddle_tpu_capi
// #include <stdlib.h>
// extern int PD_Init();
// extern void PD_Finalize();
// extern const char* PD_GetLastError();
import "C"

import "errors"

// Init boots the embedded interpreter; idempotent, call before anything.
func Init() error {
	if C.PD_Init() != 0 {
		return lastError()
	}
	return nil
}

// Finalize tears the interpreter down (optional; process exit suffices).
func Finalize() { C.PD_Finalize() }

func lastError() error {
	msg := C.GoString(C.PD_GetLastError())
	if msg == "" {
		msg = "unknown paddle_tpu capi error"
	}
	return errors.New(msg)
}
