"""Headline benchmark: BERT-base MLM pretraining tokens/sec/chip, plus
ResNet-50 images/sec/chip and BERT phase-2 (seq 512, pallas flash
attention) as secondary BASELINE.md metrics.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"loss_start", "loss_end", "median_of", "samples",
"secondary": {...resnet50...}, "secondary2": {...bert phase-2 flash...}}.

vs_baseline compares against the A100 GPU-parity target from BASELINE.md
(the reference publishes no numbers in-tree; NVIDIA DeepLearningExamples
BERT-base phase-1 pretraining, seq 128 fp16 + fused kernels, reports
~700-800 sequences/sec on one A100 ≈ 90-100k tokens/sec — we use 90000
tokens/sec/chip as the parity bar; phase-2 at seq 512 reports ~80-90k
tokens/sec — we use 85000; ResNet-50 v1.5 AMP+DALI ~2500-2900 images/sec
— we use 2500).

Recipe parity: phase-1 pretraining at seq 128 with
max_predictions_per_seq=20 (phase-2: seq 512, 80) — MLM logits are
computed only at the gathered masked positions (BertForPretraining
masked_positions path), exactly as the A100 reference recipe does; dropout
(hidden 0.1 + attention 0.1) is ON, as in the standard config. RNG uses
the TPU-native rbg implementation (framework/random.py) — part of the
measured win. Phase-2 runs the pallas flash-attention kernel
(ops/pallas/flash_attention.py): seq 512 >= FLASH_ATTENTION_MIN_SEQ, where
the XLA path OOMs at this batch and the kernel is the measured winner.

Noise discipline: the axon tunnel shows up to ±30% run-to-run variance, so
a single sample cannot certify a bar crossing. Every metric times
``repeats`` independent passes in-process and reports the MEDIAN (all
samples are included in the JSON for auditability).

Timing note: the final loss value is fetched (np.asarray), not just
block_until_ready'd — on the remote-TPU (axon) backend block_until_ready
can return before execution completes, giving absurd throughputs; a value
fetch is the reliable barrier.
"""
from __future__ import annotations

import json
import time

import numpy as np

GPU_PARITY_TOKENS_PER_SEC = 90000.0
GPU_PARITY_TOKENS_PER_SEC_PHASE2 = 85000.0
GPU_PARITY_IMAGES_PER_SEC = 2500.0

REPEATS_TPU = 3  # median-of-3: certifies bar crossings under tunnel noise


def _timed_median(step_once, items_per_iter, iters, repeats):
    """Run ``repeats`` timed passes of ``iters`` steps; return
    (median items/sec, samples, last_loss)."""
    samples = []
    last = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            m = step_once()
        last = float(np.asarray(m["loss"]))  # value fetch = barrier
        dt = time.perf_counter() - t0
        samples.append(round(items_per_iter * iters / dt, 1))
    return float(np.median(samples)), samples, last


def _utilization_fields(row, items_per_iter):
    """Attach hardware-utilization fields to a throughput row: MFU and
    HBM-bandwidth utilization from the cost model's captured per-step
    FLOPs/bytes (the compiled module's own cost_analysis, not an
    estimate) at the row's measured steps/sec — BENCH_*.json then tracks
    utilization regressions, not just absolute tokens/sec."""
    from paddle_tpu.monitor import cost_model

    rec = cost_model.latest_record("train_step")
    peaks = cost_model.device_peaks()
    steps_per_sec = row["value"] / items_per_iter if items_per_iter else 0.0
    if rec is None or not rec.flops:
        row["mfu"] = 0.0
        row["hbm_bw_util"] = 0.0
        return row
    row["mfu"] = round(cost_model.mfu(rec.flops * steps_per_sec, peaks), 5)
    row["hbm_bw_util"] = round(
        cost_model.hbm_bw_util(rec.bytes_accessed * steps_per_sec, peaks), 5)
    row["cost_model"] = {
        "flops_per_step": rec.flops,
        "bytes_per_step": rec.bytes_accessed,
        "peak_hbm_bytes": rec.peak_hbm_bytes,
        "roofline": cost_model.roofline_class(
            rec.flops, rec.bytes_accessed, peaks),
        "device_kind": peaks["kind"],
        "peaks_nominal": peaks["nominal"],
    }
    return row


def _annotate_variance(row):
    """Flag runs where even in-process samples disagree — the tunnel is
    in a degraded/contended state and the median underreports the chip."""
    s = row.get("samples", [])
    if len(s) >= 2 and row["value"]:
        spread = (max(s) - min(s)) / row["value"]
        if spread > 0.15:
            row["variance_note"] = (
                f"in-process sample spread {spread:.0%}: shared-tunnel "
                "contention; see COVERAGE.md noise model")
    return row


def bench_resnet50(on_tpu):
    """ResNet-50 images/sec/chip (BASELINE.md row 1)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu import amp
    from paddle_tpu.framework import jit as fjit
    from paddle_tpu.models import resnet50, resnet18

    if on_tpu:
        # 50 iters: the axon tunnel's final value-fetch costs ~170ms fixed;
        # at 20 iters that inflates per-step time ~8ms (15%). 50 iters
        # amortizes it below 2% — the steady-state rate a real training
        # loop (which fetches loss rarely) actually sees.
        batch, size, iters, make = 128, 224, 50, resnet50
        repeats = REPEATS_TPU
        name = "resnet50_images_per_sec_per_chip"
    else:  # CPU smoke: tiny net, tiny images
        batch, size, iters, make = 8, 32, 2, resnet18
        repeats = 1
        name = "resnet18_cpu_smoke_images_per_sec"

    paddle.seed(0)
    model = make(num_classes=1000)
    optimizer = opt.Momentum(
        learning_rate=0.1, momentum=0.9, parameters=model.parameters()
    )

    def loss_fn(m, x, y):
        with amp.auto_cast():
            logits = m(x)
        return F.cross_entropy(logits.astype("float32"), y).mean()

    step = fjit.train_step(model, optimizer, loss_fn)
    rng = np.random.RandomState(0)
    import jax

    # device-resident batch: the DataLoader's prefetch stage owns the
    # host→TPU copy in real training; the bench measures step compute.
    # (Through the axon tunnel a 77MB image batch re-upload costs ~2.5s —
    # 100x the step itself.)
    x = jax.device_put(rng.randn(batch, 3, size, size).astype("float32"))
    y = jax.device_put(rng.randint(0, 1000, (batch,)).astype("int64"))

    l0 = float(np.asarray(step(x, y)["loss"]))  # warmup/compile
    float(np.asarray(step(x, y)["loss"]))
    ips, samples, l1 = _timed_median(
        lambda: step(x, y), batch, iters, repeats
    )
    return _utilization_fields(_annotate_variance({
        "metric": name,
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(ips / GPU_PARITY_IMAGES_PER_SEC, 3)
        if on_tpu else 0.0,
        "loss_start": round(l0, 4),
        "loss_end": round(l1, 4),
        "median_of": repeats,
        "samples": samples,
    }), batch)


def bench_bert(on_tpu, phase=1):
    """BERT-base MLM pretraining tokens/sec/chip.

    phase 1: seq 128, n_pred 20, batch 128 — the headline (XLA attention
    path below FLASH_ATTENTION_MIN_SEQ, the measured winner at seq 128).
    phase 2: seq 512, n_pred 80, batch 32 — runs the pallas flash
    attention kernel (the measured winner at seq >= 512, where the plain
    XLA path exhausts HBM at this batch).
    """
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import amp
    from paddle_tpu.framework import jit as fjit
    from paddle_tpu.models import (
        BertConfig,
        BertForPretraining,
        BertPretrainingCriterion,
    )

    if on_tpu:
        cfg = BertConfig(use_flash_attention=True)  # base: 12L/768H
        if phase == 1:
            batch, seq, n_pred, iters = 128, 128, 20, 50
        else:
            batch, seq, n_pred, iters = 32, 512, 80, 25
        repeats = REPEATS_TPU
        name = ("bert_base_pretrain_tokens_per_sec_per_chip" if phase == 1
                else "bert_base_phase2_seq512_flash_tokens_per_sec_per_chip")
        bar = (GPU_PARITY_TOKENS_PER_SEC if phase == 1
               else GPU_PARITY_TOKENS_PER_SEC_PHASE2)
    else:
        cfg = BertConfig(
            vocab_size=8192, hidden_size=256, num_hidden_layers=4,
            num_attention_heads=8, intermediate_size=1024,
            max_position_embeddings=512 if phase == 2 else 128,
            use_flash_attention=(phase == 2),
        )
        if phase == 1:
            batch, seq, n_pred, iters = 8, 128, 20, 3
        else:
            batch, seq, n_pred, iters = 2, 512, 80, 2
        repeats = 1
        name = ("bert_small_cpu_smoke_tokens_per_sec" if phase == 1
                else "bert_small_cpu_smoke_phase2_tokens_per_sec")
        bar = None

    paddle.seed(0)
    model = BertForPretraining(cfg)
    crit = BertPretrainingCriterion(cfg.vocab_size)
    optimizer = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())

    def loss_fn(m, ids, tt, pos, mlm, nsp):
        with amp.auto_cast():
            pred, rel = m(ids, tt, masked_positions=pos)
        return crit(
            pred.astype("float32"), rel.astype("float32"), mlm, nsp
        )

    step = fjit.train_step(model, optimizer, loss_fn)

    rng = np.random.RandomState(0)
    # device-resident batch (see bench_resnet50 note)
    ids = jax.device_put(
        rng.randint(1, cfg.vocab_size, (batch, seq)).astype("int64")
    )
    tt = jax.device_put(rng.randint(0, 2, (batch, seq)).astype("int64"))
    # flat positions into the [B*L] hidden-state table, n_pred per sequence
    pos = jax.device_put(np.stack(
        [rng.choice(seq, n_pred, replace=False) + i * seq
         for i in range(batch)]
    ).ravel().astype("int64"))
    mlm = jax.device_put(
        rng.randint(0, cfg.vocab_size, (batch * n_pred,)).astype("int64")
    )
    nsp = jax.device_put(rng.randint(0, 2, (batch, 1)).astype("int64"))

    # warmup + compile
    loss_start = float(np.asarray(step(ids, tt, pos, mlm, nsp)["loss"]))
    float(np.asarray(step(ids, tt, pos, mlm, nsp)["loss"]))

    # the timed loop runs under a TrainingMonitor so the bench prints the
    # utilization line end-to-end (mfu/hbm_bw_util from the compiled
    # module's own cost_analysis via the executed-work ledger); per-step
    # monitor cost is inside the certified <2% monitor_overhead budget
    import sys

    from paddle_tpu import monitor as _monitor

    # stderr: bench stdout stays exactly ONE JSON line (driver contract)
    mon = _monitor.TrainingMonitor(
        f"bench_bert_phase{phase}", interval=iters,
        log_fn=lambda line: print(line, file=sys.stderr))

    def monitored_step():
        with mon.step(examples=batch * seq):
            return step(ids, tt, pos, mlm, nsp)

    tps, samples, loss_end = _timed_median(
        monitored_step, batch * seq, iters, repeats
    )
    mon.close()
    return _utilization_fields(_annotate_variance({
        "metric": name,
        "value": round(tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tps / bar, 3) if bar else 0.0,
        # convergence evidence: repeated steps on one batch must drive the
        # loss down (full loss-parity training lives in tests/test_book.py)
        "loss_start": round(loss_start, 4),
        "loss_end": round(loss_end, 4),
        "median_of": repeats,
        "samples": samples,
    }), batch * seq)


def bench_monitor_overhead(iters=300):
    """Instrumentation overhead on the executor_dispatch micro-bench.

    The whole-stack spans (RecordEvent around plan/feed/dispatch/
    writeback) ride the dispatch hot path even when nobody profiles —
    with the profiler DISABLED each span is two perf_counter_ns calls
    and a no-op end(). This row measures exactly that cost: the same
    steady-state loop with the spans live vs. with RecordEvent stubbed
    to a literal no-op, profiler off in both. The per-run cost-model
    accounting (cost_model.note_run — two counter adds feeding the MFU
    ledger) rides the same hot path, so the stubbed mode no-ops it too:
    the row certifies spans + utilization accounting together. Target:
    < 2% overhead (the always-on price of observability must be noise).
    """
    import paddle_tpu.monitor.cost_model as cost_mod
    import paddle_tpu.static.executor as executor_mod

    class _NullEvent:
        __slots__ = ("name",)

        def __init__(self, name):
            self.name = name

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

        def begin(self):
            return self

        def end(self):
            pass

    real_event = executor_mod.RecordEvent
    real_note_run = cost_mod.note_run
    live, stubbed = [], []
    # alternate modes so slow drift (thermal, competing load) hits both;
    # compare BEST-of-5 rates: scheduler/GC noise only ever slows a pass,
    # so the max of each mode is the least-contaminated estimate of its
    # true dispatch rate (medians of overlapping noisy distributions
    # routinely fabricate multi-percent "overheads" here)
    for _ in range(5):
        live.append(bench_executor_dispatch(iters=iters)["value"])
        executor_mod.RecordEvent = _NullEvent
        cost_mod.note_run = lambda record, n=1: None
        try:
            stubbed.append(bench_executor_dispatch(iters=iters)["value"])
        finally:
            executor_mod.RecordEvent = real_event
            cost_mod.note_run = real_note_run
    live_best = float(max(live))
    stub_best = float(max(stubbed))
    # overhead of the live spans relative to the stubbed loop; negative
    # means the difference drowned in run-to-run noise (good)
    overhead = (stub_best - live_best) / stub_best
    # DIRECT decomposition of the per-run cost-accounting price (the
    # flight-recorder row's discipline): a whole-loop A/B cannot resolve
    # 2% on a contended box, but the tight-loop per-call cost of
    # note_run (the only per-run work the cost model adds — two counter
    # adds) divided by the measured run period is noise-immune.
    import time as _time

    rec = cost_mod.latest_record("executor")

    def _note_us(n=20000):
        t0 = _time.perf_counter()
        for _ in range(n):
            real_note_run(rec)
        return (_time.perf_counter() - t0) / n * 1e6

    note_us = min(_note_us() for _ in range(3))
    period_us = 1e6 / live_best
    cost_overhead = note_us / period_us  # one note_run per executor run
    return {
        "metric": "executor_dispatch_instrumentation_overhead",
        "value": round(overhead * 100, 2),
        "unit": "percent",
        "target_pct": 2.0,
        "within_target": bool(overhead < 0.02),
        "instrumented_runs_per_sec": live_best,
        "stubbed_runs_per_sec": stub_best,
        "best_of": 5,
        "samples": {"instrumented": live, "stubbed": stubbed},
        "cost_accounting": {
            "per_note_run_us": round(note_us, 3),
            "run_period_us": round(period_us, 1),
            "overhead_pct": round(cost_overhead * 100, 3),
            "within_target": bool(cost_overhead < 0.02),
        },
    }


def bench_flight_recorder_overhead(iters=300):
    """Flight-recorder cost on the executor_dispatch micro-bench.

    Recording is always-on (FLAGS_flight_recorder defaults True): every
    run() appends 2 structured events to the ring buffer (one flag read
    + dict build + short lock hold each). Target: < 2% — the black box
    must be free enough to never turn off.

    Measurement discipline: a whole-loop A/B cannot resolve 2% on a
    contended box (the dispatch bench itself swings ±20% run to run —
    observed sign flips across repeats), so the certified number is the
    DIRECT decomposition: per-event record cost (tight loop, on minus
    off, best-of-3 — the only quantity noise at this scale can't bury)
    × events actually recorded per run ÷ the measured steady-state run
    period. The whole-loop A/B (best-of-5 per mode, alternating) ships
    alongside as corroboration; on a quiet box both agree.
    """
    import time as _time

    from paddle_tpu.flags import get_flags, set_flags
    from paddle_tpu.monitor import flight_recorder as fr

    def _per_event_us(n=20000):
        t0 = _time.perf_counter()
        for _ in range(n):
            fr.record_event(
                "bench_probe", program="p@v1", plan_cache="hit",
                jit_cache="hit", feeds=2, fetches=1, donated=4)
        return (_time.perf_counter() - t0) / n * 1e6

    prev = get_flags("flight_recorder")["flight_recorder"]
    recording, disabled = [], []
    try:
        set_flags({"flight_recorder": True})
        on_us = min(_per_event_us() for _ in range(3))
        # events per run + steady-state period, with recording live
        rec = fr.get_recorder()
        before = rec.total_recorded
        live_row = bench_executor_dispatch(iters=iters)
        events_per_run = (
            (rec.total_recorded - before) / float(live_row["runs"]))
        period_us = 1e6 / live_row["value"]
        set_flags({"flight_recorder": False})
        off_us = min(_per_event_us() for _ in range(3))
        # whole-loop A/B corroboration (alternating so drift hits both)
        for _ in range(5):
            set_flags({"flight_recorder": True})
            recording.append(bench_executor_dispatch(iters=iters)["value"])
            set_flags({"flight_recorder": False})
            disabled.append(bench_executor_dispatch(iters=iters)["value"])
    finally:
        set_flags({"flight_recorder": prev})
    per_event_delta_us = max(0.0, on_us - off_us)
    overhead = per_event_delta_us * events_per_run / period_us
    rec_best, off_best = float(max(recording)), float(max(disabled))
    return {
        "metric": "flight_recorder_overhead",
        "value": round(overhead * 100, 3),
        "unit": "percent",
        "target_pct": 2.0,
        "within_target": bool(overhead < 0.02),
        "per_event_us": {"recording": round(on_us, 3),
                         "disabled": round(off_us, 3),
                         "delta": round(per_event_delta_us, 3)},
        "events_per_run": round(events_per_run, 2),
        "run_period_us": round(period_us, 1),
        "ab_corroboration": {
            "overhead_pct": round(
                (off_best - rec_best) / off_best * 100, 2),
            "recording_runs_per_sec": rec_best,
            "disabled_runs_per_sec": off_best,
            "best_of": 5,
            "samples": {"recording": recording, "disabled": disabled},
        },
    }


def bench_goodput_overhead(iters_direct=20000):
    """Goodput-ledger cost on the training step path (target < 1%).

    The ledger touches a step exactly at its phase transitions:
    ``step_begin`` / ``step_commit`` bracket the frame, and each
    sub-phase feed (``note_phase`` for input wait, the checkpoint /
    compile spans) is one more lock-held float add. A whole-loop A/B
    can't resolve sub-percent cost (monitor_overhead discipline), so
    the certified number is the DIRECT decomposition: per-transition
    cost (tight loop on an in-memory ledger, best-of-3) × transitions
    per step ÷ the measured steady-state dispatch period.
    """
    import time as _time

    from paddle_tpu.monitor.goodput import GoodputLedger

    led = GoodputLedger(dir=None)  # in-memory: no sidecar, no metrics

    def _per_frame_us(n=iters_direct):
        t0 = _time.perf_counter()
        for _ in range(n):
            led.step_begin()
            led.step_commit()
        return (_time.perf_counter() - t0) / n * 1e6

    def _per_note_us(n=iters_direct):
        t0 = _time.perf_counter()
        for _ in range(n):
            led.note_phase("input_wait", 0.0)
        return (_time.perf_counter() - t0) / n * 1e6

    frame_us = min(_per_frame_us() for _ in range(3))
    note_us = min(_per_note_us() for _ in range(3))
    # steady-state step period from the dispatch micro-bench (the same
    # reference period every observability overhead row certifies
    # against)
    live_row = bench_executor_dispatch(iters=200)
    period_us = 1e6 / live_row["value"]
    # a representative step: one frame + input-wait note + one
    # amortized sub-phase span (checkpoint/compile every few steps)
    notes_per_step = 2.0
    step_cost_us = frame_us + note_us * notes_per_step
    overhead = step_cost_us / period_us
    return {
        "metric": "goodput_overhead",
        "value": round(overhead * 100, 3),
        "unit": "percent",
        "target_pct": 1.0,
        "within_target": bool(overhead < 0.01),
        "per_frame_us": round(frame_us, 3),
        "per_note_us": round(note_us, 3),
        "notes_per_step": notes_per_step,
        "step_period_us": round(period_us, 1),
    }


def bench_opprof_overhead(iters_direct=20000):
    """Per-op attribution cost on the dispatch path (target < 1%).

    The op stamps (``op.type#<block>/<index>`` named_scope, executor
    _exec_one) are written only while an op walk is TRACING — a plan-
    cache miss. A steady-state dispatch replays the compiled callable
    and never touches them, so the certified idle number is the direct
    decomposition of the trace-time cost amortized over the window it
    buys: per-stamp cost (format + named_scope enter/exit, tight loop,
    best-of-3) × ops per trace epoch ÷ (dispatches per epoch × the
    measured dispatch period). Sampling-mode cost — one on-demand
    ``profile_program`` replay — is reported unasserted: it runs only
    when explicitly requested, never on the dispatch path, and is
    bounded by warmup+repeats per op.
    """
    import jax

    from paddle_tpu.monitor import opprof

    def _per_stamp_us(n=iters_direct):
        scope = opprof.op_scope_name
        t0 = time.perf_counter()
        for i in range(n):
            with jax.named_scope(scope("matmul", 0, i & 63)):
                pass
        return (time.perf_counter() - t0) / n * 1e6

    stamp_us = min(_per_stamp_us() for _ in range(3))
    live_row = bench_executor_dispatch(iters=200)
    period_us = 1e6 / live_row["value"]
    # a trace epoch = one plan-cache miss; the dispatch bench's train
    # step (fwd+grad+Adam) walks ~24 ops once and then serves at least
    # the bench window of dispatches from the cache
    ops_per_trace = 24.0
    dispatches_per_trace = 200.0
    overhead = (stamp_us * ops_per_trace) / (
        dispatches_per_trace * period_us)

    # sampling mode: replay-profile a small program once, wall-clock
    import paddle_tpu.static as static
    from paddle_tpu import ops

    static.enable_static()
    static.reset_default_programs()
    static.global_scope().clear()
    try:
        x = static.data("x", [32, 64], "float32")
        w = static.nn.create_parameter([64, 16], "float32")
        out = ops.relu(ops.matmul(x, w))
        exe = static.Executor()
        exe.run_startup()
        feeds = {"x": np.random.RandomState(0).randn(32, 64)
                 .astype("float32")}
        exe.run(feed=feeds, fetch_list=[out])
        t0 = time.perf_counter()
        prof = opprof.profile_program(
            static.default_main_program(), feeds, name="bench",
            with_trace=False, record=False)
        sample_ms = (time.perf_counter() - t0) * 1e3
    finally:
        static.disable_static()
        static.reset_default_programs()
        static.global_scope().clear()

    return {
        "metric": "opprof_overhead",
        "value": round(overhead * 100, 4),
        "unit": "percent",
        "target_pct": 1.0,
        "within_target": bool(overhead < 0.01),
        "per_stamp_us": round(stamp_us, 3),
        "ops_per_trace": ops_per_trace,
        "dispatches_per_trace": dispatches_per_trace,
        "step_period_us": round(period_us, 1),
        "sampling": {
            "profile_ms": round(sample_ms, 1),
            "ops_replayed": prof["replayed_ops"],
            "time_accuracy": prof["time_accuracy"],
        },
    }


def bench_tracing_overhead(requests=160, iters_direct=4000):
    """Per-request tracing cost on the serving path (target < 2%).

    Every served request records a span tree (root + queue-wait +
    assemble + dispatch and its fan-in copy) through the tail-sampled
    trace store; tracing ships always-on, so the cost must be certified
    the way ``monitor_overhead``/``flight_recorder_overhead`` are.

    Discipline: the certified number is the DIRECT decomposition — the
    per-span cost of a representative span tree (enabled minus disabled,
    tight loop, best-of-3: the quantity box noise cannot bury) scaled by
    the spans a real request actually records, over the measured
    per-request period of a live batcher+replica loop. The whole-loop
    A/B (alternating, best-of-5) ships alongside as corroboration.
    """
    import tempfile
    import time as _time

    import paddle_tpu.static as static
    from paddle_tpu.flags import get_flags, set_flags
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.monitor import tracing
    from paddle_tpu.serving import DynamicBatcher, ReplicaPool

    # a 5-span tree per iteration: the serving request's shape
    def _per_tree_us(n=iters_direct):
        t0 = _time.perf_counter()
        for _ in range(n):
            with tracing.start_trace("bench::request"):
                with tracing.start_span("bench::queue_wait"):
                    pass
                with tracing.start_span("bench::assemble", bucket=4,
                                        fill=1.0):
                    pass
                with tracing.start_span("bench::dispatch", flops=1.0):
                    pass
                with tracing.start_span("bench::reply", status=200):
                    pass
        return (_time.perf_counter() - t0) / n * 1e6

    static.enable_static()
    static.reset_default_programs()
    static.global_scope().clear()
    try:
        x = static.data("x", [None, 32], "float32")
        y = static.nn.fc(static.nn.fc(x, 64, name="tr_fc1"), 8,
                         name="tr_fc2")
        exe = static.Executor()
        exe.run_startup()
        model_dir = tempfile.mkdtemp(prefix="ptpu_bench_trace_")
        static.save_inference_model(model_dir, ["x"], [y], exe)
    finally:
        static.disable_static()
        static.reset_default_programs()
    pred = create_predictor(Config(model_dir))
    batcher = DynamicBatcher(["x"], buckets=(1, 2, 4),
                             queue_capacity=64, batch_timeout_ms=0.5)
    pool = ReplicaPool(pred, batcher, replicas=2)
    pool.warmup()
    pool.start()
    rng = np.random.RandomState(0)
    feeds = [rng.randn((i % 3) + 1, 32).astype("float32")
             for i in range(requests)]

    def _request_loop():
        """One closed-loop client, a trace root per request — the HTTP
        frontend's shape without the socket noise."""
        t0 = _time.perf_counter()
        for a in feeds:
            with tracing.start_trace("serving::bench"):
                batcher.predict({"x": a}, timeout=30)
        return (_time.perf_counter() - t0) / len(feeds) * 1e6

    prev = get_flags("trace_enabled")["trace_enabled"]
    traced, untraced = [], []
    try:
        set_flags({"trace_enabled": True})
        on_us = min(_per_tree_us() for _ in range(3))
        # spans per request, measured not assumed: flag one live trace
        # so the sampler must retain it, then count its spans
        with tracing.start_trace("serving::bench_probe") as root:
            tracing.flag_current_trace("bench")
            batcher.predict({"x": feeds[0]}, timeout=30)
        payload = tracing.store().get(root.trace_id)
        spans_per_request = len(payload["spans"]) if payload else 5
        period_us = _request_loop()
        set_flags({"trace_enabled": False})
        off_us = min(_per_tree_us() for _ in range(3))
        # whole-loop A/B corroboration (alternating so drift hits both)
        for _ in range(5):
            set_flags({"trace_enabled": True})
            traced.append(_request_loop())
            set_flags({"trace_enabled": False})
            untraced.append(_request_loop())
    finally:
        set_flags({"trace_enabled": prev})
        pool.stop(drain=False)
        tracing.reset_store()
    per_span_delta_us = max(0.0, on_us - off_us) / 5.0
    overhead = per_span_delta_us * spans_per_request / period_us
    t_best, u_best = float(min(traced)), float(min(untraced))
    return {
        "metric": "tracing_overhead",
        "value": round(overhead * 100, 3),
        "unit": "percent",
        "target_pct": 2.0,
        "within_target": bool(overhead < 0.02),
        "per_span_us": {"traced": round(on_us / 5.0, 3),
                        "disabled": round(off_us / 5.0, 3),
                        "delta": round(per_span_delta_us, 3)},
        "spans_per_request": spans_per_request,
        "request_period_us": round(period_us, 1),
        "ab_corroboration": {
            "overhead_pct": round((t_best - u_best) / u_best * 100, 2),
            "traced_request_us": round(t_best, 1),
            "untraced_request_us": round(u_best, 1),
            "best_of": 5,
            "samples": {"traced": [round(v, 1) for v in traced],
                        "untraced": [round(v, 1) for v in untraced]},
        },
    }


def bench_observability_overhead(requests=160, iters_direct=20000,
                                 backends=8):
    """Labeled metric families + /fleetz merge cost (target < 2%).

    The SLO plane adds two prices. (1) The hot serving path now observes
    into LABELED histogram children (child lookup under the family lock
    plus parent propagation) where it used to observe a bare histogram —
    certified with the tracing row's discipline: the tight-loop
    per-observe delta (labeled minus bare, best-of-3) scaled by the
    labeled observes one served predict request records (queue-wait +
    e2e = 2), over the measured per-request period of a live
    batcher+replica loop. (2) The router's fleet merge — per-backend
    ``registry_snapshot()`` serialization plus the label-aware
    elementwise bucket merge across the fleet — measured directly and
    reported per scrape; it runs on the PROBER thread, so it is reported
    against the probe period, not the request period.
    """
    import tempfile
    import time as _time

    import paddle_tpu.static as static
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.monitor import (histogram, merge_histogram_snapshots,
                                    registry_snapshot)
    from paddle_tpu.serving import DynamicBatcher, ReplicaPool

    # serving-shaped bucket ladder; distinct names so the registry's
    # real serving families stay untouched
    ladder = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
              1000.0)
    h_bare = histogram("bench/obs_bare_ms", buckets=ladder)
    h_lab = histogram("bench/obs_labeled_ms", buckets=ladder)

    def _bare_us(n=iters_direct):
        t0 = _time.perf_counter()
        for _ in range(n):
            h_bare.observe(7.0)
        return (_time.perf_counter() - t0) / n * 1e6

    def _labeled_us(n=iters_direct):
        # the batcher resolves labels() per observe (tenant varies per
        # request), so the lookup is part of the certified price
        t0 = _time.perf_counter()
        for _ in range(n):
            h_lab.labels(kind="predict", bucket="4",
                         tenant="default").observe(7.0)
        return (_time.perf_counter() - t0) / n * 1e6

    bare_us = min(_bare_us() for _ in range(3))
    labeled_us = min(_labeled_us() for _ in range(3))
    per_observe_delta_us = max(0.0, labeled_us - bare_us)

    # live request period: same mini-model loop the tracing row uses
    static.enable_static()
    static.reset_default_programs()
    static.global_scope().clear()
    try:
        x = static.data("x", [None, 32], "float32")
        y = static.nn.fc(static.nn.fc(x, 64, name="ob_fc1"), 8,
                         name="ob_fc2")
        exe = static.Executor()
        exe.run_startup()
        model_dir = tempfile.mkdtemp(prefix="ptpu_bench_obs_")
        static.save_inference_model(model_dir, ["x"], [y], exe)
    finally:
        static.disable_static()
        static.reset_default_programs()
    pred = create_predictor(Config(model_dir))
    batcher = DynamicBatcher(["x"], buckets=(1, 2, 4),
                             queue_capacity=64, batch_timeout_ms=0.5)
    pool = ReplicaPool(pred, batcher, replicas=2)
    pool.warmup()
    pool.start()
    rng = np.random.RandomState(0)
    feeds = [rng.randn((i % 3) + 1, 32).astype("float32")
             for i in range(requests)]
    try:
        t0 = _time.perf_counter()
        for a in feeds:
            batcher.predict({"x": a}, timeout=30)
        period_us = (_time.perf_counter() - t0) / len(feeds) * 1e6
    finally:
        pool.stop(drain=False)
    observes_per_request = 2  # predict path: queue-wait + e2e
    overhead = per_observe_delta_us * observes_per_request / period_us

    # fleet merge: one backend snapshot serialization + the label-aware
    # merge across the fleet's serving histograms (prober-thread work)
    for v in (3.0, 30.0, 300.0):
        for t in ("a", "b", "c"):
            h_lab.labels(kind="predict", bucket="4", tenant=t).observe(v)
    t0 = _time.perf_counter()
    snap_reps = 20
    for _ in range(snap_reps):
        snap = registry_snapshot()
    snapshot_us = (_time.perf_counter() - t0) / snap_reps * 1e6
    hist_snaps = {name: s for name, s in snap.items()
                  if isinstance(s, dict) and s.get("kind") == "histogram"}
    fleet = [hist_snaps] * backends
    t0 = _time.perf_counter()
    merge_reps = 20
    for _ in range(merge_reps):
        for name in hist_snaps:
            merge_histogram_snapshots([b[name] for b in fleet],
                                      name=name)
    merge_us = (_time.perf_counter() - t0) / merge_reps * 1e6
    return {
        "metric": "observability_overhead",
        "value": round(overhead * 100, 3),
        "unit": "percent",
        "target_pct": 2.0,
        "within_target": bool(overhead < 0.02),
        "per_observe_us": {"labeled": round(labeled_us, 3),
                           "bare": round(bare_us, 3),
                           "delta": round(per_observe_delta_us, 3)},
        "observes_per_request": observes_per_request,
        "request_period_us": round(period_us, 1),
        "fleet_merge": {
            "backends": backends,
            "histograms": len(hist_snaps),
            "snapshot_us": round(snapshot_us, 1),
            "merge_us": round(merge_us, 1),
            "per_scrape_us": round(snapshot_us + merge_us, 1),
        },
    }


def bench_serving_throughput(requests=120, rows_cycle=(1, 2, 3, 4),
                             levels=(1, 4, 16)):
    """Online-serving throughput: the dynamic batcher + replica pool vs
    sequential single-request Predictor calls on the same model.

    Sequential baseline: one thread, one ``Predictor.run`` per request
    (each distinct row count warmed first, so it pays per-request
    dispatch but no compiles — the OLD inference story at its best).
    Batched: an offered-load sweep — ``levels`` concurrent clients
    pushing the same request mix through the batcher — reporting
    requests/sec per level, mean batch fill, p50/p99 end-to-end latency
    from the serving histograms, and the compile accounting (bounded at
    the bucket-ladder length, asserted).

    fp32-vs-int8 sub-metric: the same model is PTQ-calibrated, saved
    through ``save_int8_model`` and driven through the same sequential
    steady-state loop — reporting int8 requests/sec, the speed ratio,
    and the max output delta vs the fp32 program (the accuracy half of
    the cost-per-token tradeoff; on the CPU smoke the speedup is noise,
    on TPU the int8 HBM/MXU savings are the point).
    """
    import tempfile

    import paddle_tpu.static as static
    from paddle_tpu import monitor, profiler, slim
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.monitor import histogram_quantile
    from paddle_tpu.serving import DynamicBatcher, ReplicaPool

    static.enable_static()
    static.reset_default_programs()
    static.global_scope().clear()
    try:
        x = static.data("x", [None, 64], "float32")
        h = static.nn.fc(x, 512, name="serve_fc1")
        h = static.nn.fc(h, 512, name="serve_fc2")
        y = static.nn.fc(h, 8, name="serve_fc3")
        exe = static.Executor()
        exe.run_startup()
        model_dir = tempfile.mkdtemp(prefix="ptpu_bench_serve_")
        static.save_inference_model(model_dir, ["x"], [y], exe)
        # int8 twin of the same program: calibrate on the request
        # distribution, fold the scales into a deployable int8 save
        rng_cal = np.random.RandomState(7)
        calib = [{"x": rng_cal.randn(8, 64).astype("float32")}
                 for _ in range(4)]
        ptq = slim.PostTrainingQuantization(exe, static
                                            .default_main_program(), calib)
        ptq.quantize()
        int8_dir = tempfile.mkdtemp(prefix="ptpu_bench_serve_int8_")
        ptq.save_int8_model(int8_dir, ["x"], [y])
    finally:
        static.disable_static()
        static.reset_default_programs()
        static.global_scope().clear()
    pred = create_predictor(Config(model_dir))

    rng = np.random.RandomState(0)
    reqs = [rng.randn(rows_cycle[i % len(rows_cycle)], 64).astype("float32")
            for i in range(requests)]

    # -- sequential baseline (steady state: per-shape warmup first) -------
    for r in sorted(set(rows_cycle)):
        pred.run([rng.randn(r, 64).astype("float32")])
    t0 = time.perf_counter()
    fp32_outs = []
    for a in reqs:
        fp32_outs.append(np.asarray(pred.run([a])[0]))
    seq_rps = requests / (time.perf_counter() - t0)

    # -- int8 A/B on the same loop ----------------------------------------
    pred8 = create_predictor(Config(int8_dir))
    for r in sorted(set(rows_cycle)):
        pred8.run([rng.randn(r, 64).astype("float32")])
    t0 = time.perf_counter()
    int8_outs = []
    for a in reqs:
        int8_outs.append(np.asarray(pred8.run([a])[0]))
    int8_rps = requests / (time.perf_counter() - t0)
    out_scale = max(np.abs(o).max() for o in fp32_outs)
    max_delta = max(np.abs(a - b).max()
                    for a, b in zip(fp32_outs, int8_outs))

    # -- batched path through the serving stack ---------------------------
    import threading

    batcher = DynamicBatcher(["x"], buckets=(1, 2, 4, 8),
                             queue_capacity=max(64, requests),
                             batch_timeout_ms=1.0)
    pool = ReplicaPool(pred, batcher, replicas=2)
    pool.warmup()
    pool.start()
    counters0 = profiler.counters()
    sweep = []
    try:
        for level in levels:
            per_client = max(1, requests // level)

            def client(cid):
                r = np.random.RandomState(cid)
                for i in range(per_client):
                    a = r.randn(rows_cycle[i % len(rows_cycle)],
                                64).astype("float32")
                    batcher.predict({"x": a}, timeout=60)

            t0 = time.perf_counter()
            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(level)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            sweep.append({"concurrency": level,
                          "requests": per_client * level,
                          "req_per_sec": round(per_client * level / dt, 1)})
        snap = monitor.registry_snapshot()
        rows_done = snap["serving/batched_rows_total"]["value"]
        slots = snap["serving/batch_slots_total"]["value"]
        h_e2e = monitor.histogram("serving/e2e_ms")
        best = max(s["req_per_sec"] for s in sweep)
        extra = pool.extra_compiles()
        return {
            "metric": "serving_throughput",
            "value": best,
            "unit": "requests/sec",
            "sequential_req_per_sec": round(seq_rps, 1),
            "speedup_vs_sequential": round(best / seq_rps, 3),
            "int8_ab": {
                "int8_req_per_sec": round(int8_rps, 1),
                "int8_vs_fp32": round(int8_rps / seq_rps, 3),
                "max_output_delta": round(float(max_delta), 6),
                "max_output_delta_rel": round(
                    float(max_delta / out_scale), 6),
            },
            "offered_load_sweep": sweep,
            "mean_batch_fill": round(rows_done / slots, 4) if slots else 0.0,
            "p50_ms": round(histogram_quantile(h_e2e, 0.5), 3),
            "p99_ms": round(histogram_quantile(h_e2e, 0.99), 3),
            "compiles": {
                "buckets": 4,
                "extra_after_warmup": extra,
                "jit_misses_total": profiler.counters().get(
                    "executor::jit_cache_miss", 0)
                - counters0.get("executor::jit_cache_miss", 0),
            },
        }
    finally:
        pool.stop(drain=True)
        static.global_scope().clear()


def bench_router_throughput(requests=640, rows_cycle=(1, 2, 3, 4),
                            backend_counts=(1, 2), clients_per_backend=24):
    """Serving fleet scaling: an offered-load sweep over 1 -> N
    independent backend PROCESSES behind the router, vs the same load on
    a single backend.

    Each backend is a real ``python -m paddle_tpu.serving.backend``
    subprocess (own interpreter, own XLA client, own registry) booted by
    the scaler's SubprocessLauncher, and the router runs as ITS OWN
    process too (``python -m paddle_tpu.serving.router`` — an in-bench
    router would share the client threads' GIL and cap the whole sweep
    at one core of Python) — process-level parallelism end to end, not
    the thread-level replica pool the ``serving_throughput`` row
    measures. Reports requests/sec and rows/sec per fleet size, the
    1->N speedup (the near-linear scaling acceptance), fleet p50/p99
    merged from the backends' /histz bucket counts, and per-backend
    compile accounting scraped from /loadz (each backend exactly
    len(ladder) jit misses, zero unexpected — the bounded-compile
    discipline holds per process).
    """
    import os
    import subprocess
    import tempfile
    import threading
    from urllib.request import urlopen

    import paddle_tpu.static as static
    from paddle_tpu.serving import SubprocessLauncher

    buckets = (1, 2, 4, 8)
    static.enable_static()
    static.reset_default_programs()
    static.global_scope().clear()
    try:
        # wide enough that one backend process is genuinely compute-
        # bound well below the client side's capacity — the sweep must
        # measure BACKEND scaling, not the load generator's ceiling
        x = static.data("x", [None, 64], "float32")
        h = static.nn.fc(x, 4096, name="rt_fc1")
        h = static.nn.fc(h, 4096, name="rt_fc2")
        h = static.nn.fc(h, 4096, name="rt_fc3")
        y = static.nn.fc(h, 8, name="rt_fc4")
        exe = static.Executor()
        exe.run_startup()
        model_dir = tempfile.mkdtemp(prefix="ptpu_bench_router_")
        static.save_inference_model(model_dir, ["x"], [y], exe)
    finally:
        static.disable_static()
        static.reset_default_programs()

    from paddle_tpu.serving.scaler import launch_process

    # core layout: disjoint sets per backend (on one box XLA:CPU would
    # otherwise spread each backend's intra-op threads across EVERY
    # core and co-hosted backends would contend for the same silicon —
    # pinning emulates one host per backend, what a real fleet has),
    # the router on its own pair, the load generator on the rest.
    # Boxes too small to split run everything unpinned — the scaling
    # number is then contention-limited, but the row still runs.
    ncores = os.cpu_count() or 1
    # 2 cores per backend: small enough that neither shared DRAM
    # bandwidth (the 4096-wide weights stream from memory every
    # dispatch) nor the single-process load generator approaches its
    # ceiling before the second backend shows — measured headroom is
    # what makes the scaling number repeatable
    per = min(2, ncores // (max(backend_counts) + 1))
    cpu_sets = ([f"{i * per}-{(i + 1) * per - 1}"
                 for i in range(max(backend_counts))]
                if per >= 1 else None)
    n_backend_cores = per * max(backend_counts) if cpu_sets else 0
    router_cores = None
    orig_affinity = None
    if cpu_sets and ncores > n_backend_cores + 2:
        router_cores = f"{n_backend_cores}-{n_backend_cores + 1}"
        try:
            orig_affinity = os.sched_getaffinity(0)
            os.sched_setaffinity(
                0, set(range(n_backend_cores + 2, ncores)))
        except (AttributeError, OSError):
            orig_affinity = None
    launcher = SubprocessLauncher(model_dir, buckets=buckets,
                                  batch_timeout_ms=1.0, replicas=2,
                                  queue_capacity=max(64, requests),
                                  cpu_sets=cpu_sets)

    def spawn_router(urls):
        """Router as its own process (shared launch_process recipe:
        PYTHONPATH, port-file-when-ready, taskset); (proc, url)."""
        args = ["--probe-interval-s", "1.0"]
        for u in urls:
            args += ["--backend", u]
        h = launch_process("paddle_tpu.serving.router", args,
                           cpus=router_cores, startup_timeout_s=120)
        return h.proc, h.url

    payloads = []
    rng = np.random.RandomState(0)
    for i in range(max(requests // (clients_per_backend
                            * max(backend_counts)), 1)):
        rows = rows_cycle[i % len(rows_cycle)]
        payloads.append(json.dumps({
            "inputs": rng.randn(rows, 64).astype("float32").tolist()
        }).encode())
    rows_per_client = sum(
        rows_cycle[i % len(rows_cycle)] for i in range(len(payloads)))

    sweep = []
    try:
        for n in backend_counts:
            # WEAK scaling: offered load grows with the fleet (a fleet
            # exists because traffic grew) — a fixed closed-loop client
            # count would hand each fleet backend a shallower queue and
            # worse batch fill than the solo baseline enjoyed, and the
            # sweep would measure that artifact, not capacity
            clients = clients_per_backend * n
            handles = [launcher.launch() for _ in range(n)]
            rproc, rurl = spawn_router([h.url for h in handles])
            try:
                failures = []
                from http.client import HTTPConnection
                from urllib.parse import urlsplit

                ru = urlsplit(rurl)
                # all clients connect + warm OUTSIDE the timed window
                # (a closed-loop sweep otherwise times its own
                # ramp-up), then release together per trial
                barrier = None

                def post_one(conn, body):
                    try:
                        conn.request("POST", "/predict", body=body,
                                     headers={"Content-Type":
                                              "application/json"})
                        resp = conn.getresponse()
                        resp.read()
                        if resp.status != 200:
                            failures.append(f"HTTP {resp.status}")
                        if resp.will_close:
                            conn.close()
                            conn = HTTPConnection(ru.hostname, ru.port,
                                                  timeout=60)
                    except Exception as e:  # noqa: BLE001
                        failures.append(repr(e))
                        conn.close()
                        conn = HTTPConnection(ru.hostname, ru.port,
                                              timeout=60)
                    return conn

                def client(cid):
                    # keep-alive load generator: one persistent
                    # connection per client (a connection-per-request
                    # generator measures TCP/thread churn, not the
                    # fleet)
                    conn = HTTPConnection(ru.hostname, ru.port,
                                          timeout=60)
                    try:
                        for body in payloads[:2]:  # untimed warmup
                            conn = post_one(conn, body)
                        barrier.wait()
                        for body in payloads:
                            conn = post_one(conn, body)
                    finally:
                        conn.close()

                # best-of-2 timed trials (the deeply saturated
                # closed loop is noisy at the few-percent level; the
                # ratio of two levels doubles that)
                dts = []
                for _trial in range(2):
                    barrier = threading.Barrier(clients + 1)
                    threads = [threading.Thread(target=client,
                                                args=(c,))
                               for c in range(clients)]
                    for t in threads:
                        t.start()
                    barrier.wait()
                    t0 = time.perf_counter()
                    for t in threads:
                        t.join()
                    dts.append(time.perf_counter() - t0)
                    assert not failures, failures[:3]
                dt = min(dts)
                per_backend = []
                for h in handles:
                    lz = json.loads(urlopen(h.url + "/loadz").read())
                    assert lz["compiles"]["jit_misses"] == len(buckets), lz
                    assert lz["compiles"]["unexpected"] == 0, lz
                    per_backend.append({
                        "url": h.url,
                        "compiles": lz["compiles"],
                        "mean_fill": lz["mean_fill"],
                    })
                sz = json.loads(urlopen(rurl + "/statz").read())
                assert (sz["fleet"]["requests"]
                        >= len(payloads) * clients), sz["fleet"]
                merged = sz["latency"]["backends_merged"][
                    "serving/e2e_ms"]
                total = len(payloads) * clients
                sweep.append({
                    "backends": n,
                    "requests": total,
                    "req_per_sec": round(total / dt, 1),
                    "rows_per_sec": round(
                        rows_per_client * clients / dt, 1),
                    "p50_ms": merged["p50_ms"],
                    "p99_ms": merged["p99_ms"],
                    "per_backend": per_backend,
                })
            finally:
                rproc.terminate()
                try:
                    rproc.wait(15)
                except subprocess.TimeoutExpired:
                    rproc.kill()
                for h in handles:
                    launcher.terminate(h, drain=True)
    finally:
        if orig_affinity is not None:
            # the affinity squeeze is sweep-local: the remaining bench
            # rows must see the whole machine again
            try:
                os.sched_setaffinity(0, orig_affinity)
            except OSError:
                pass
    base = sweep[0]["req_per_sec"]
    best = sweep[-1]
    return {
        "metric": "router_throughput",
        "value": best["req_per_sec"],
        "unit": "requests/sec",
        "scaling_vs_one_backend": round(best["req_per_sec"] / base, 3),
        "scaling_target": 1.6,
        "offered_load_sweep": sweep,
        "compiles_per_backend_expected": len(buckets),
    }


def bench_decode_throughput(requests=16, slots=4, cache_len=64,
                            prefill_buckets=(8, 16)):
    """Generative decoding: continuous batching vs static batching on a
    mixed-length request sweep.

    Static baseline: requests grouped into batches of ``slots``; a group
    runs until its LONGEST member finishes (finished slots idle — the
    tear-down-and-reassemble serving model). Continuous: a finished
    sequence vacates its slot mid-batch and the next request is admitted
    at the next step, so slots stay full across the same sweep. Both run
    the SAME engine (same compiled prefill/decode programs); the only
    variable is slot turnover. Reports per-chip tokens/sec, per-token
    latency, the continuous/static speedup, compile accounting (exactly
    len(prefill ladder) + 1 programs), and decode MFU from the
    cost-model ledger.

    KV-cache economics sub-metric: the same sweep re-runs on an int8-KV
    engine (``FLAGS_generation_kv_cache_dtype=int8`` semantics) over the
    same weights — reporting ``kv_bytes_per_token`` per mode and
    ``slots_at_equal_hbm`` (how many int8 slots the fp32 cache's HBM
    buys, measured on the real cache arrays), the capacity multiplier
    decode capacity is bound by.
    """
    import paddle_tpu as paddle
    from paddle_tpu import monitor, profiler
    from paddle_tpu.generation import COMPILE_COUNTER, GenerationEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.monitor import cost_model as _cost

    paddle.seed(0)
    cfg = GPTConfig(
        vocab_size=512, hidden_size=128, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=256,
        max_position_embeddings=256, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, attention_window=cache_len)
    model = GPTForCausalLM(cfg)
    engine = GenerationEngine(model, slots=slots, cache_len=cache_len,
                              prefill_buckets=prefill_buckets)
    c0 = profiler.counters().get(COMPILE_COUNTER, 0)
    engine.warmup()
    warm_compiles = profiler.counters().get(COMPILE_COUNTER, 0) - c0

    # mixed sweep: short and long generations interleaved — the case
    # where static batching pays max(budget) per group
    rng = np.random.RandomState(1)
    prompts = [list(map(int, rng.randint(3, 500, size=int(n))))
               for n in rng.randint(2, prefill_buckets[-1] + 1,
                                    size=requests)]
    budgets = [int(b) for b in rng.randint(4, 33, size=requests)]

    # drive the engine primitives directly for BOTH modes so the
    # comparison is pure scheduling policy (no HTTP/thread noise);
    # static admits a new group only once EVERY slot has drained
    from collections import deque

    def drive(eng, continuous):
        pending = deque(zip(prompts, budgets))
        active = {}
        last = np.zeros(slots, np.int32)
        temps = np.zeros(slots, np.float32)
        done_tokens = 0
        steps = 0
        t0 = time.perf_counter()
        while pending or active:
            can_admit = bool(pending) and (continuous or not active)
            while can_admit and pending and len(active) < slots:
                free = next(s for s in range(slots) if s not in active)
                p, b = pending.popleft()
                tok = eng.admit(free, p)
                done_tokens += 1
                if b <= 1:
                    continue
                active[free] = b - 1
                last[free] = tok
            if not active:
                continue
            if eng.speculative:
                # one draft+verify round emits 1..k+1 tokens per slot
                # (truncated at each request's budget, the scheduler
                # semantics)
                nxt, counts = eng.spec_step(last, temps,
                                            busy=list(active))
                steps += 1
                for s in list(active):
                    take = min(int(counts[s]), active[s])
                    done_tokens += take
                    last[s] = nxt[s, take - 1]
                    active[s] -= take
                    if active[s] <= 0:
                        del active[s]
                continue
            nxt = eng.step(last, temps)
            steps += 1
            for s in list(active):
                done_tokens += 1
                last[s] = nxt[s]
                active[s] -= 1
                if active[s] <= 0:
                    del active[s]
        dt = time.perf_counter() - t0
        return done_tokens, steps, dt

    flops0 = monitor.registry_snapshot().get(
        "cost/executed_flops", {}).get("value", 0.0)
    static_tokens, static_steps, static_dt = drive(engine, continuous=False)
    cont_tokens, cont_steps, cont_dt = drive(engine, continuous=True)
    executed = (monitor.registry_snapshot().get(
        "cost/executed_flops", {}).get("value", 0.0) - flops0)
    assert static_tokens == cont_tokens, "both modes decode the sweep"
    extra = engine.extra_compiles()

    # -- int8 KV cache on the same sweep (after the fp32 accounting
    # closes: the int8 engine's own warmup compiles and drive FLOPs must
    # not pollute the fp32 row's extra-compile/MFU numbers) -------------
    fp32_cache_bytes = engine.cache_nbytes()
    engine8 = GenerationEngine(model, slots=slots, cache_len=cache_len,
                               prefill_buckets=prefill_buckets,
                               kv_cache_dtype="int8")
    engine8.warmup()
    int8_tokens, int8_steps, int8_dt = drive(engine8, continuous=True)
    assert int8_tokens == cont_tokens, "int8 KV decodes the same sweep"
    assert engine8.extra_compiles() == 0, "int8 decode stays compile-bound"
    int8_cache_bytes = engine8.cache_nbytes()
    slots_at_equal_hbm = int(slots * fp32_cache_bytes / int8_cache_bytes)
    peaks = _cost.device_peaks()
    cont_tps = cont_tokens / cont_dt
    static_tps = static_tokens / static_dt

    # -- speculative decoding on the same sweep (after everything
    # above closes its accounting): a 1-layer truncated draft proposes
    # k tokens, the target verifies k+1 in one batched forward — the
    # decode-is-serial lever. Greedy budgets make the sweep token-count
    # identical; the per-k engine is warmed LAST so its extra_compiles
    # reads exactly its own steady state. ---------------------------------
    from paddle_tpu.models import truncated_draft

    draft = truncated_draft(model, num_layers=1)
    speculative = {"draft_layers": 1}
    for k in (2, 4):
        eng_k = GenerationEngine(model, slots=slots, cache_len=cache_len,
                                 prefill_buckets=prefill_buckets,
                                 draft_model=draft, draft_k=k)
        warm0 = profiler.counters().get(COMPILE_COUNTER, 0)
        eng_k.warmup()
        warm_k = profiler.counters().get(COMPILE_COUNTER, 0) - warm0
        assert warm_k == eng_k.expected_compiles(), (
            warm_k, eng_k.expected_compiles())
        spec_tokens, spec_rounds, spec_dt = drive(eng_k, continuous=True)
        assert spec_tokens == cont_tokens, \
            "speculative decodes the same sweep"
        assert eng_k.extra_compiles() == 0, \
            "speculative decode stays compile-bound"
        stats = eng_k.spec_stats()
        spec_tps = spec_tokens / spec_dt
        speculative[f"k{k}"] = {
            "tokens_per_sec": round(spec_tps, 1),
            "ms_per_token": round(1e3 * spec_dt / spec_tokens, 3),
            "rounds": spec_rounds,
            "acceptance_rate": stats["acceptance_rate"],
            "vs_plain_tokens_per_sec": round(spec_tps / cont_tps, 3),
            "warmup_compiles": warm_k,
        }
    return {
        "metric": "decode_throughput",
        "value": round(cont_tps, 1),
        "unit": "tokens/sec",
        "requests": requests,
        "slots": slots,
        "tokens_generated": cont_tokens,
        "continuous": {
            "tokens_per_sec": round(cont_tps, 1),
            "decode_steps": cont_steps,
            "ms_per_token": round(1e3 * cont_dt / cont_tokens, 3),
        },
        "static": {
            "tokens_per_sec": round(static_tps, 1),
            "decode_steps": static_steps,
            "ms_per_token": round(1e3 * static_dt / static_tokens, 3),
        },
        "speedup_continuous_vs_static": round(cont_tps / static_tps, 3),
        "speculative": speculative,
        "kv_cache": {
            "fp32_bytes_per_token": engine.kv_bytes_per_token(),
            "int8_bytes_per_token": engine8.kv_bytes_per_token(),
            "fp32_cache_bytes": fp32_cache_bytes,
            "int8_cache_bytes": int8_cache_bytes,
            "slots_at_equal_hbm": slots_at_equal_hbm,
            "int8_tokens_per_sec": round(int8_tokens / int8_dt, 1),
            "int8_vs_fp32_tokens_per_sec": round(
                (int8_tokens / int8_dt) / cont_tps, 3),
        },
        "compiles": {
            "warmup": warm_compiles,
            "expected": len(prefill_buckets) + 1,
            "extra_after_warmup": extra,
        },
        "mfu_decode": round(
            _cost.mfu(executed / (static_dt + cont_dt), peaks), 6),
        "device_kind": peaks.get("kind"),
    }


def bench_paged_kv(cache_len=64, page_size=4,
                   prefill_buckets=(4, 8, 16, 32, 48, 64), slots=4):
    """Paged KV cache vs the contiguous ring on three axes.

    Shared-prefix sweep (the tentpole economics): requests repeating a
    templated prefix at 0/25/50/75/90/95% of the prompt admit through
    the radix prefix index — matched full pages are retained (CoW
    shared), and only the unmatched suffix is prefilled, in the
    smallest bucket that holds it. Per ratio the row reports the
    measured per-tenant hit rate, the prefill-FLOPs-saved fraction
    ``1 - suffix_bucket/full_bucket`` (program-size accounting — on a
    bucketed ladder the saving is exactly the bucket shrink), and
    measured TTFT (admit wall time), which must scale down together.

    Capacity: the SAME mixed short/long sweep that needs ``slots`` full
    ring windows runs token-identically on a page pool 1.6x smaller —
    short requests hold only the pages they touch and idle prefix-cache
    pages evict under pressure — i.e. >= 1.3x slots at equal HBM.

    Parity: every paged row above decodes the ring engine's exact
    greedy tokens, at exactly len(prefill ladder) + 1 compiled programs
    (the unified full/suffix prefill is ONE program per bucket;
    ``shared_len`` is a traced scalar, not a shape).
    """
    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.generation import COMPILE_COUNTER, GenerationEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(
        vocab_size=512, hidden_size=128, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=256,
        max_position_embeddings=256, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, attention_window=cache_len)
    model = GPTForCausalLM(cfg)
    ring = GenerationEngine(model, slots=slots, cache_len=cache_len,
                            prefill_buckets=prefill_buckets)
    ring.warmup()
    eng = GenerationEngine(model, slots=slots, cache_len=cache_len,
                           prefill_buckets=prefill_buckets,
                           kv_cache_layout="paged",
                           kv_page_size=page_size)
    c0 = profiler.counters().get(COMPILE_COUNTER, 0)
    eng.warmup()
    warm_compiles = profiler.counters().get(COMPILE_COUNTER, 0) - c0

    # -- parity: mixed burst decodes the ring's exact greedy tokens ----
    rng = np.random.RandomState(7)
    mixed = [list(map(int, rng.randint(3, 500, size=n)))
             for n in (6, 48, 3, 40, 12, 30, 7, 24)]
    want = ring.generate(mixed, max_new_tokens=8, temperature=0.0)
    got = eng.generate(mixed, max_new_tokens=8, temperature=0.0)
    assert got == want, "paged layout diverged from the ring goldens"
    assert eng.extra_compiles() == 0, "paged burst must stay compile-bound"

    # -- shared-prefix sweep: hit rate, FLOPs saved, TTFT per ratio ----
    def bucket_for(n):
        return next(b for b in prefill_buckets if b >= max(n, 1))

    full = prefill_buckets[-1]
    sweep = []
    for share in (0.0, 0.25, 0.5, 0.75, 0.9, 0.95):
        shared_n = int(share * full) // page_size * page_size
        prefix = list(map(int, rng.randint(3, 500, size=shared_n)))
        tenant = f"share{int(share * 100)}"
        ttfts = []
        for _ in range(4):  # 1 cold admit populates the index + 3 warm
            req = prefix + list(map(int, rng.randint(
                3, 500, size=full - shared_n)))
            t0 = time.perf_counter()
            eng.admit(0, req, 0.0, tenant=tenant)
            ttfts.append(time.perf_counter() - t0)
            eng.release_slot(0)
        st = eng.paging_stats()["per_tenant"][tenant]
        suffix_bucket = bucket_for(full - shared_n)
        sweep.append({
            "share": share,
            "shared_tokens": shared_n,
            "measured_hit_rate": st["hit_rate"],
            "suffix_bucket": suffix_bucket,
            "prefill_flops_saved": round(1.0 - suffix_bucket / full, 4),
            "ttft_cold_ms": round(1e3 * ttfts[0], 3),
            "ttft_reused_ms": round(
                1e3 * sorted(ttfts[1:])[len(ttfts[1:]) // 2], 3),
        })
    assert eng.extra_compiles() == 0, (
        "suffix prefill recompiled; shared_len must be traced")
    extra = eng.extra_compiles()  # before the cap engine's own warmup
    index = eng.paging_stats()["prefix_index"]

    # -- slots at equal HBM: the mixed sweep on a 1.6x-smaller pool ----
    ring_equiv_pages = slots * (cache_len // page_size)
    pool_pages = int(ring_equiv_pages / 1.6)
    cap = GenerationEngine(model, slots=slots, cache_len=cache_len,
                           prefill_buckets=prefill_buckets,
                           kv_cache_layout="paged",
                           kv_page_size=page_size,
                           kv_pool_pages=pool_pages)
    cap.warmup()
    got_cap = cap.generate(mixed, max_new_tokens=8, temperature=0.0)
    assert got_cap == want, "mixed burst diverged on the constrained pool"
    assert cap.extra_compiles() == 0, (
        "constrained pool must not change the compiled programs' count")
    cap_stats = cap.paging_stats()
    slots_ratio = ring_equiv_pages / pool_pages
    return {
        "metric": "paged_kv",
        "value": round(slots_ratio, 3),
        "unit": "x_slots_at_equal_hbm",
        "page_size": page_size,
        "cache_len": cache_len,
        "parity_prompts": len(mixed),
        "shared_prefix_sweep": sweep,
        "prefix_index": {
            "lookups": index["lookups"],
            "hits": index["hits"],
            "hit_rate": index["hit_rate"],
            "evictions": index["evictions"],
        },
        "slots_at_equal_hbm": {
            "ring_equiv_pages": ring_equiv_pages,
            "pool_pages": pool_pages,
            "peak_pages_used": cap_stats["peak_pages_used"],
            "cow_copies": cap_stats["cow_copies"],
            "ratio": round(slots_ratio, 3),
        },
        "compiles": {
            "warmup": warm_compiles,
            "expected": len(prefill_buckets) + 1,
            "extra_after_warmup": extra,
        },
        "kv_bytes_per_token": eng.kv_bytes_per_token(),
        "page_nbytes": eng.page_nbytes(),
    }


def bench_disagg_fleet(requests=36, clients=12):
    """Disaggregated prefill/decode fleet vs a unified fleet at EQUAL
    backend count (2 processes each) on a mixed prompt-length sweep.

    Unified: two ``--kind generate`` backends, each splitting its slots
    between serving decode steps and running its own prefills. Disagg:
    one ``--kind prefill`` backend (all compute on the bucket-ladder
    forward, ships KV slabs) + one ``--kind decode`` backend whose
    capacity is ALL decode slots — the asymmetry disaggregation buys:
    prefill scales on compute, decode on HBM, so the decode tier
    dedicates its whole memory budget to slots (2x the unified fleet's
    total here) where a unified backend must also hold prefill
    activations and share its loop between the two phases. The router
    (its own process, like the backends) orchestrates the prompt ->
    slab -> decode handoff. The offered load oversubscribes the
    unified fleet's slots (clients > unified slots), which is where
    the slot-wait tail lives.

    Clients stream (``"stream": true``) so TTFT is measured CLIENT-side
    — submit to first token line through the router, the number a user
    sees — under long-budget background generations that keep decode
    slots busy: the unified fleet's p99 arrival waits for a slot on a
    loop that is also prefilling, the disaggregated fleet's waits only
    on the dedicated decode tier. Reports TTFT p50/p99 and
    tokens/sec(/chip) per fleet shape, with per-backend compile
    accounting asserted from /loadz (zero unexpected on every process
    — the handoff path compiles nothing).
    """
    import json as _json
    import signal as _signal
    import tempfile
    import threading
    from urllib.request import Request, urlopen

    import paddle_tpu as paddle
    from paddle_tpu.models import (
        GPTConfig,
        GPTForCausalLM,
        save_gpt_model,
    )
    from paddle_tpu.serving.scaler import launch_process

    cache_len = 64
    buckets = "16,64"
    paddle.seed(7)
    cfg = GPTConfig(
        vocab_size=512, hidden_size=128, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=256,
        max_position_embeddings=256, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, attention_window=cache_len)
    gpt_dir = tempfile.mkdtemp(prefix="ptpu_bench_disagg_")
    save_gpt_model(GPTForCausalLM(cfg), gpt_dir)

    rng = np.random.RandomState(3)
    prompts = [list(map(int, rng.randint(3, 500, size=int(n))))
               for n in rng.randint(8, 65, size=requests)]
    budgets = [int(b) for b in rng.randint(24, 65, size=requests)]

    def boot_backend(kind, slots):
        args = ["--kind", kind, "--gpt-dir", gpt_dir,
                "--cache-len", str(cache_len),
                "--prefill-buckets", buckets,
                "--slots", str(slots),
                "--queue-capacity", "64"]
        return launch_process("paddle_tpu.serving.backend", args,
                              startup_timeout_s=180)

    def boot_router(urls):
        args = ["--probe-interval-s", "0.5"]
        for u in urls:
            args += ["--backend", u]
        return launch_process("paddle_tpu.serving.router", args,
                              startup_timeout_s=120)

    def run_fleet(shape):
        if shape == "unified":
            backends = [boot_backend("generate", 3),
                        boot_backend("generate", 3)]
        else:
            backends = [boot_backend("prefill", 1),
                        boot_backend("decode", 14)]
        router = boot_router([b.url for b in backends])
        ttfts, tokens_out, errs = [], [0], []
        lock = threading.Lock()
        work = list(zip(prompts, budgets))

        def client(idx):
            for i in range(idx, len(work), clients):
                p, b = work[i]
                body = _json.dumps({
                    "prompt": p, "max_new_tokens": b,
                    "temperature": 0.0, "stream": True}).encode()
                t0 = time.perf_counter()
                try:
                    r = urlopen(Request(
                        router.url + "/generate", data=body,
                        headers={"Content-Type": "application/json"}),
                        timeout=300)
                    first = None
                    n = 0
                    for line in r:
                        msg = _json.loads(line)
                        if "token" in msg:
                            if first is None:
                                first = time.perf_counter() - t0
                            n += 1
                        if "error" in msg:
                            raise RuntimeError(msg["error"])
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errs.append(f"{type(e).__name__}: {e}")
                    continue
                with lock:
                    if first is not None:
                        ttfts.append(first * 1e3)
                    tokens_out[0] += n

        try:
            # settle the prober's kind map before offering load
            time.sleep(1.5)
            t0 = time.perf_counter()
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            assert not errs, errs[:3]
            assert len(ttfts) == requests, (len(ttfts), requests)
            # per-process compile accounting: the handoff path must
            # compile NOTHING beyond each kind's warmup set
            compiles = {}
            for b in backends:
                lz = _json.loads(urlopen(b.url + "/loadz",
                                         timeout=10).read())
                assert lz["compiles"]["unexpected"] == 0, (b.url, lz)
                compiles[lz["kind"]] = lz["compiles"]
            ttfts.sort()
            return {
                "ttft_p50_ms": round(ttfts[len(ttfts) // 2], 1),
                "ttft_p99_ms": round(ttfts[min(len(ttfts) - 1, int(
                    len(ttfts) * 0.99))], 1),
                "tokens_per_sec": round(tokens_out[0] / wall, 1),
                "tokens_per_sec_per_chip": round(
                    tokens_out[0] / wall / len(backends), 1),
                "backends": len(backends),
                "compiles": compiles,
            }
        finally:
            for h in [router] + backends:
                try:
                    h.proc.send_signal(_signal.SIGTERM)
                except OSError:
                    pass
            for h in [router] + backends:
                try:
                    h.proc.wait(20)
                except Exception:  # noqa: BLE001
                    h.proc.kill()

    unified = run_fleet("unified")
    disagg = run_fleet("disagg")
    return {
        "metric": "disagg_fleet",
        "value": disagg["ttft_p99_ms"],
        "unit": "ms (ttft p99, disaggregated)",
        "requests": requests,
        "clients": clients,
        "unified": unified,
        "disaggregated": disagg,
        "ttft_p99_disagg_vs_unified": round(
            disagg["ttft_p99_ms"] / unified["ttft_p99_ms"], 3),
    }


def bench_checkpoint_overhead(steps=150, every=25):
    """Async-checkpoint cost on the training step path.

    The preemption-tolerance contract (ISSUE 8 / ROADMAP item 5) is only
    free if snapshotting does not slow training: the capture is a
    device-side copy of the state pytree (donation-safe) dispatched
    async; serialize + fsync + atomic publish run on the background
    writer thread. This row runs the same deterministic train loop three
    ways — no checkpointing, a BLOCKING save every ``every`` steps (the
    reference's save-on-the-step-path behavior), and the async path —
    and reports the step-loop overhead of each vs the no-checkpoint
    baseline. Target: async < 2% (the blocking column is the price it
    replaces). The drain (wait for the last writes after the loop) is
    reported separately — it overlaps training everywhere except the
    final step.
    """
    import shutil
    import tempfile

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as popt
    from paddle_tpu.distributed import checkpoint as ckpt
    from paddle_tpu.framework import jit as fjit

    # compute-heavy, state-light: large batch over a narrow MLP keeps the
    # step on the XLA compute path for milliseconds while the snapshot
    # payload stays ~300KB — the realistic regime (any sane checkpoint
    # interval makes save bytes tiny next to inter-save compute; on real
    # accelerators the step doesn't even share cores with the writer)
    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(96, 96)
            self.fc2 = nn.Linear(96, 96)
            self.fc3 = nn.Linear(96, 16)

        def forward(self, x):
            return self.fc3(F.relu(self.fc2(F.relu(self.fc1(x)))))

    def loss_fn(m, x, y):
        return F.cross_entropy(m(x), y).mean()

    rng = np.random.RandomState(0)
    X = rng.randn(2048, 96).astype("float32")
    Y = rng.randint(0, 16, (2048,)).astype("int64")

    def build():
        paddle.seed(11)
        m = MLP()
        o = popt.Adam(learning_rate=0.01, parameters=m.parameters())
        return fjit.train_step(m, o, loss_fn)

    def run(mode, outdir):
        step = build()
        step(X, Y)  # compile outside the timed window
        saves = 0
        t0 = time.perf_counter()
        m = None
        for s in range(steps):
            m = step(X, Y)
            if mode != "none" and (s + 1) % every == 0:
                step.save_checkpoint(
                    f"{outdir}/step_{s}", step=s, keep=2,
                    async_=(mode == "async"))
                saves += 1
        loss = float(np.asarray(m["loss"]))  # value fetch = barrier
        loop_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        ckpt.wait_pending()
        drain_s = time.perf_counter() - t1
        return loop_s, drain_s, saves, loss

    root = tempfile.mkdtemp(prefix="ptpu_ckpt_bench_")
    try:
        # interleave arms best-of-3 so machine noise hits all three alike
        best = {"none": None, "blocking": None, "async": None}
        for _ in range(3):
            for mode in best:
                out = run(mode, f"{root}/{mode}")
                if best[mode] is None or out[0] < best[mode][0]:
                    best[mode] = out
        base_s, _, _, loss_none = best["none"]
        blk_s, _, n_saves, loss_blk = best["blocking"]
        asn_s, drain_s, _, loss_asn = best["async"]
        asn_pct = (asn_s - base_s) / base_s * 100.0
        blk_pct = (blk_s - base_s) / base_s * 100.0
        assert abs(loss_blk - loss_none) < 1e-6  # snapshots don't perturb
        assert abs(loss_asn - loss_none) < 1e-6

        # direct decomposition (monitor_overhead discipline): the step
        # path pays exactly the capture+submit of save_checkpoint — time
        # it in isolation and amortize over the save interval. The
        # whole-loop A/B above corroborates but swings with box noise;
        # this number is what the <2% contract is gated on.
        step = build()
        step(X, Y)
        step.save_checkpoint(f"{root}/direct/warm", step=0, async_=True)
        ckpt.wait_pending()
        t0 = time.perf_counter()
        for i in range(20):
            step.save_checkpoint(f"{root}/direct/s{i}", step=i,
                                 async_=True)
        capture_ms = (time.perf_counter() - t0) / 20 * 1e3
        ckpt.wait_pending()
        step_ms = base_s / steps * 1e3
        direct_pct = capture_ms / (every * step_ms) * 100.0
        return {
            "metric": "checkpoint_step_overhead_pct",
            "value": round(direct_pct, 3),
            "unit": "% of step time (capture+submit / save interval)",
            "steps": steps,
            "save_every": every,
            "saves": n_saves,
            "capture_submit_ms": round(capture_ms, 3),
            "baseline_steps_per_sec": round(steps / base_s, 1),
            "async_steps_per_sec": round(steps / asn_s, 1),
            "blocking_steps_per_sec": round(steps / blk_s, 1),
            "loop_async_overhead_pct": round(asn_pct, 3),
            "loop_blocking_overhead_pct": round(blk_pct, 3),
            "async_drain_ms": round(drain_s * 1e3, 3),
            "target_met": bool(direct_pct < 2.0),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_fused_kernels(iters=150, overlap_batches=40):
    """Fused-kernel + input-overlap A/B (the ResNet-gap levers).

    Three decompositions, each fused-vs-unfused on the SAME math (the
    fused jnp fallback is bit-identical, so off-TPU the ratio measures
    XLA's fusion of both forms and should sit near 1.0; on TPU the
    fused side runs the pallas kernels):

    - ``optimizer_update``: one Momentum(+wd) update over a ResNet-ish
      parameter set, µs/step tight-loop A/B (jitted, value-fetch
      barrier) — the kernel's one-VMEM-pass claim.
    - ``layernorm_residual``: the post-norm transformer's add+norm pair
      at BERT-base shape, fused op vs the two-op chain.
    - ``conv_bn_relu``: the ResNet triple at a mid-stage shape, the
      fused pallas dispatch vs the unfused conv2d->batch_norm->relu op
      chain (off-TPU both run the identical jnp sequence, ratio ~1.0).
    - ``autotune``: tuned-vs-default µs per kernel from a live
      best-of-N schedule search (save=False — the bench never mutates
      the process's tuning cache), the ROADMAP item-3 evidence row.
    - ``train_loop``: whole-loop corroboration — compiled Momentum
      steps on a small conv net with the flags on vs off (numerics
      asserted identical; wall-clock ratio is the honest end-to-end
      answer, noisier than the micro rows).

    Plus ``input_overlap``: the monitor's input-wait accounting driven
    through ``_DevicePrefetcher`` with a deliberately slow source and a
    fixed consumer step, overlap off vs on — the before/after
    input-wait ratio is the proof the H2D/parse work left the step
    path.
    """
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as popt
    from paddle_tpu.flags import get_flags, set_flags
    from paddle_tpu.framework import jit as fjit
    from paddle_tpu.framework.tensor import to_tensor
    from paddle_tpu.ops.pallas import fused_momentum_update

    import jax

    def _best_us(fn, *args, n=5):
        fn(*args)  # warm/compile
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out)
            best = min(best, time.perf_counter() - t0)
        return best * 1e6

    import jax.numpy as jnp_mod

    rng = np.random.RandomState(0)

    # -- optimizer update µs/step -----------------------------------------
    shapes = [(256, 256)] * 6 + [(1024, 256)] * 2 + [(1024,)] * 4
    params = [jnp_mod.asarray(rng.randn(*s).astype("f4")) for s in shapes]
    grads = [jnp_mod.asarray(rng.randn(*s).astype("f4")) for s in shapes]
    vels = [jnp_mod.asarray(np.zeros(s, "f4")) for s in shapes]

    def fused_all(ps, gs, vs, lr):
        out = [fused_momentum_update(p, g, v, lr, 0.9, 1e-4)
               for p, g, v in zip(ps, gs, vs)]
        return [o[0] for o in out], [o[1] for o in out]

    def unfused_all(ps, gs, vs, lr):
        new_p, new_v = [], []
        for p, g, v in zip(ps, gs, vs):
            g = g + 1e-4 * p
            v = 0.9 * v + g
            new_p.append(p - lr * v)
            new_v.append(v)
        return new_p, new_v

    lr = jnp_mod.asarray(0.1, jnp_mod.float32)
    opt_fused_us = _best_us(jax.jit(fused_all), params, grads, vels, lr)
    opt_unfused_us = _best_us(jax.jit(unfused_all), params, grads, vels, lr)

    # -- layernorm+residual µs/step ----------------------------------------
    from paddle_tpu.ops.pallas import layernorm_residual as _lnr_fn

    h = 768
    x = jnp_mod.asarray(rng.randn(8, 128, h).astype("f4"))
    res = jnp_mod.asarray(rng.randn(8, 128, h).astype("f4"))
    w = jnp_mod.asarray(np.ones(h, "f4"))
    b = jnp_mod.asarray(np.zeros(h, "f4"))

    def unfused_ln(x, res, w, b):
        a = x + res
        mean = jnp_mod.mean(a, axis=-1, keepdims=True)
        var = jnp_mod.var(a, axis=-1, keepdims=True)
        return (a - mean) * jax.lax.rsqrt(var + 1e-5) * w + b

    ln_fused_us = _best_us(
        jax.jit(lambda x, res, w, b: _lnr_fn(x, res, w, b, 1e-5)),
        x, res, w, b)
    ln_unfused_us = _best_us(jax.jit(unfused_ln), x, res, w, b)

    # -- conv+bn+relu µs/step (the ResNet triple) --------------------------
    import sys as _sys

    from paddle_tpu.ops.pallas import conv_bn_relu as _cbr_fn  # noqa: F401

    _cbr = _sys.modules["paddle_tpu.ops.pallas.conv_bn_relu"]
    xc = jnp_mod.asarray(rng.randn(8, 64, 16, 16).astype("f4"))
    wc = jnp_mod.asarray(rng.randn(128, 64, 3, 3).astype("f4") * 0.05)
    gam = jnp_mod.asarray(np.ones(128, "f4"))
    bet = jnp_mod.asarray(np.zeros(128, "f4"))
    rmean = jnp_mod.asarray(np.zeros(128, "f4"))
    rvar = jnp_mod.asarray(np.ones(128, "f4"))
    cbr_kw = dict(stride=1, padding=1, training=True, momentum=0.9,
                  eps=1e-5, data_format="NCHW")
    cbr_fused_us = _best_us(
        jax.jit(lambda x, w: _cbr._fused(x, w, gam, bet, rmean, rvar,
                                         **cbr_kw)[0]), xc, wc)
    cbr_unfused_us = _best_us(
        jax.jit(lambda x, w: _cbr._reference(x, w, gam, bet, rmean, rvar,
                                             **cbr_kw)[0]), xc, wc)

    # -- autotune sub-row: tuned-vs-default µs per kernel ------------------
    # a real (small) offline search per kernel, save=False so the bench
    # never mutates the process's tuning cache; on TPU these time the
    # pallas kernels, on CPU the interpret-mode pipeline (selection
    # logic identical, absolute numbers nominal)
    from paddle_tpu import tuning as _tuning

    autotune = {}
    tuner = _tuning.KernelTuner(measure_n=3)
    for kernel, info, cands in (
        ("layernorm_residual",
         dict(rows=256, h=512, dtype="float32"),
         [{"block_r": 16}, {"block_r": 64}, {"block_r": 256}]),
        ("conv_bn_relu",
         dict(m=512, k=64, c=128, dtype="float32"),
         [{"tile_m": 64}, {"tile_m": 256}]),
    ):
        try:
            r = tuner.tune(kernel, candidates=cands, save=False, **info)
            autotune[kernel] = {
                "tuned_us": round(r.best_us, 1),
                "default_us": (round(r.default_us, 1)
                               if r.default_us is not None else None),
                "speedup": round(r.speedup, 3),
                "params": r.params,
                "measured": r.measured,
                "pruned": r.pruned,
            }
        except Exception as e:  # a failed search is a report, not a crash
            autotune[kernel] = {"error": f"{type(e).__name__}: {e}"}

    # -- whole-loop corroboration ------------------------------------------
    def train_loop():
        paddle.seed(5)
        net = nn.Linear(128, 64)
        opt = popt.Momentum(learning_rate=0.05, momentum=0.9,
                            weight_decay=1e-4,
                            parameters=net.parameters())
        step = fjit.train_step(
            net, opt, lambda m, x, y: F.mse_loss(m(x), y).mean())
        loop_rng = np.random.RandomState(17)  # same data both arms
        X = loop_rng.randn(64, 128).astype("f4")
        Y = loop_rng.randn(64, 64).astype("f4")
        step(X, Y)  # compile
        t0 = time.perf_counter()
        m = None
        for _ in range(iters):
            m = step(X, Y)
        loss = float(np.asarray(m["loss"]))
        return time.perf_counter() - t0, loss

    prev = get_flags(["use_fused_optimizer", "use_fused_layernorm"])
    try:
        set_flags({"use_fused_optimizer": True,
                   "use_fused_layernorm": True})
        fused_s, fused_loss = train_loop()
        set_flags({"use_fused_optimizer": False,
                   "use_fused_layernorm": False})
        unfused_s, unfused_loss = train_loop()
    finally:
        set_flags(prev)
    assert abs(fused_loss - unfused_loss) < 1e-5  # the fusion is free

    # -- input overlap ------------------------------------------------------
    from paddle_tpu.io.dataloader import _DevicePrefetcher
    from paddle_tpu.monitor import registry as _reg

    def drive(overlap):
        def source():
            for i in range(overlap_batches):
                time.sleep(0.002)  # parse/collate latency
                yield np.full((16, 16), i, np.float32)

        set_flags({"io_prefetch_overlap": overlap})
        gauge = _reg.gauge("io/input_wait_ms")
        wait0 = gauge.value
        pf = _DevicePrefetcher(source(), depth=2, to_device=True)
        t0 = time.perf_counter()
        for _ in pf:
            time.sleep(0.002)  # the consumer's "step"
        wall = time.perf_counter() - t0
        return wall, (gauge.value - wait0) / (wall * 1e3)

    prev_ov = get_flags("io_prefetch_overlap")["io_prefetch_overlap"]
    try:
        sync_wall, ratio_before = drive(False)
        overlap_wall, ratio_after = drive(True)
    finally:
        set_flags({"io_prefetch_overlap": prev_ov})

    return {
        "metric": "fused_kernels",
        "value": round(opt_unfused_us / opt_fused_us, 3),
        "unit": "optimizer-update speedup (fused vs unfused)",
        "optimizer_update": {
            "fused_us": round(opt_fused_us, 1),
            "unfused_us": round(opt_unfused_us, 1),
            "speedup": round(opt_unfused_us / opt_fused_us, 3),
        },
        "layernorm_residual": {
            "fused_us": round(ln_fused_us, 1),
            "unfused_us": round(ln_unfused_us, 1),
            "speedup": round(ln_unfused_us / ln_fused_us, 3),
        },
        "conv_bn_relu": {
            "fused_us": round(cbr_fused_us, 1),
            "unfused_us": round(cbr_unfused_us, 1),
            "speedup": round(cbr_unfused_us / cbr_fused_us, 3),
        },
        # per-kernel tuned-vs-default from a live (save=False) search
        "autotune": autotune,
        "train_loop": {
            "fused_steps_per_sec": round(iters / fused_s, 1),
            "unfused_steps_per_sec": round(iters / unfused_s, 1),
            "speedup": round(unfused_s / fused_s, 3),
            "loss_identical": True,
        },
        "input_overlap": {
            "batches": overlap_batches,
            "sync_wall_ms": round(sync_wall * 1e3, 1),
            "overlap_wall_ms": round(overlap_wall * 1e3, 1),
            "wall_speedup": round(sync_wall / overlap_wall, 3),
            "input_wait_ratio_before": round(ratio_before, 4),
            "input_wait_ratio_after": round(ratio_after, 4),
        },
    }


def bench_executor_dispatch(iters=200):
    """Static-graph Executor steady-state dispatch micro-bench.

    Runs one small compiled train step ``iters+1`` times through
    Executor.run and reports dispatches/sec plus the executor's
    plan-cache / jit-cache / donation counters (profiler.counters): in
    steady state every run after the first must be a plan-cache hit — the
    op walk runs exactly once — and the written persistables are donated.
    """
    import paddle_tpu.static as static
    from paddle_tpu import ops, profiler

    static.enable_static()
    static.reset_default_programs()
    static.global_scope().clear()
    try:
        x = static.data("x", [32, 64], "float32")
        y = static.data("y", [32, 1], "float32")
        w = static.nn.create_parameter([64, 1], "float32")
        pred = ops.matmul(x, w)
        loss = ops.mean(ops.square(ops.subtract(pred, y)))
        opt = static.optimizer.Adam(learning_rate=0.01)
        opt.minimize(loss)
        exe = static.Executor()
        exe.run_startup()
        rng = np.random.RandomState(0)
        X = rng.randn(32, 64).astype("float32")
        Y = rng.randn(32, 1).astype("float32")

        profiler.reset_counters()
        exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])  # compile
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])
        loss_end = float(np.asarray(out[0]))  # value fetch = barrier
        dt = time.perf_counter() - t0
        counters = {k: v for k, v in profiler.counters().items()
                    if k.startswith("executor::")}

        # program_verify sub-row: the IR verifier runs once per program
        # MUTATION EPOCH (the verdict caches on the Program per version,
        # static/program.py Program.verify), so a steady-state dispatch
        # pays only the flag read + cache lookup. Direct decomposition
        # (the monitor_overhead discipline — end-to-end A/B of a ~1ms
        # dispatch cannot resolve a ~1us cost on a noisy box): time the
        # cached verify call itself and express it as a fraction of the
        # measured dispatch period; budget <1%.
        prog = static.default_main_program()
        feedns, fetchns = ["x", "y"], [loss.name]
        prog.verify(feed_names=feedns, fetch_list=fetchns)  # warm the cache
        # best-of batches: the first post-compile loop otherwise eats the
        # XLA-garbage GC pauses and reports 20x the true lookup cost
        reps, cached_us = 400, float("inf")
        for _ in range(5):
            tv = time.perf_counter()
            for _ in range(reps):
                prog.verify(feed_names=feedns, fetch_list=fetchns)
            cached_us = min(cached_us,
                            (time.perf_counter() - tv) / reps * 1e6)
        period_us = dt / iters * 1e6
        tfull = time.perf_counter()
        prog._verify_cache.clear()
        prog.verify(feed_names=feedns, fetch_list=fetchns)
        full_verify_us = (time.perf_counter() - tfull) * 1e6
        verify_overhead = cached_us / period_us

        # memplan sub-row: the peak-HBM admission gate
        # (FLAGS_memory_budget_check) pays a cached verdict lookup per
        # dispatch and ONE full liveness plan per program mutation
        # epoch — same direct-decomposition discipline as the
        # program_verify sub-row, same <1% budget. plan_accuracy comes
        # from the accuracy closure the steady-state loop's first
        # compile already ledgered (predicted vs XLA memory_analysis).
        from paddle_tpu.analysis import memory as _memplan
        from paddle_tpu.monitor import cost_model as _cost

        shapes = {"x": (32, 64), "y": (32, 1)}
        _memplan.check_memory_budget(prog, feedns, fetchns,
                                     feed_shapes=shapes)  # warm
        mem_cached_us = float("inf")
        for _ in range(5):
            tv = time.perf_counter()
            for _ in range(reps):
                _memplan.check_memory_budget(prog, feedns, fetchns,
                                             feed_shapes=shapes)
            mem_cached_us = min(mem_cached_us,
                                (time.perf_counter() - tv) / reps * 1e6)
        tfull = time.perf_counter()
        prog._memplan_cache.clear()
        plan = _memplan.check_memory_budget(prog, feedns, fetchns,
                                            feed_shapes=shapes)
        full_plan_us = (time.perf_counter() - tfull) * 1e6
        mem_overhead = mem_cached_us / period_us
        rec = _cost.latest_record("executor")

        return {
            "metric": "executor_steady_state_dispatches_per_sec",
            "value": round(iters / dt, 1),
            "unit": "runs/sec",
            "runs": iters + 1,
            "loss_end": round(loss_end, 4),
            "counters": counters,
            "program_verify": {
                # cached verdict cost paid by EVERY dispatch vs the
                # one-time full pass paid per program mutation epoch
                "cached_verify_us": round(cached_us, 3),
                "full_verify_us": round(full_verify_us, 1),
                "dispatch_period_us": round(period_us, 1),
                "overhead_pct": round(verify_overhead * 100, 3),
                "within_target": bool(verify_overhead < 0.01),
            },
            "memplan": {
                # steady-state admission = feed-shape tuples + one dict
                # lookup; the full liveness plan is per mutation epoch
                "cached_check_us": round(mem_cached_us, 3),
                "full_plan_us": round(full_plan_us, 1),
                "dispatch_period_us": round(period_us, 1),
                "overhead_pct": round(mem_overhead * 100, 3),
                "within_target": bool(mem_overhead < 0.01),
                "predicted_peak_bytes": (
                    plan.peak_bytes if plan is not None else None),
                "peak_op": (f"#{plan.peak_op_index} "
                            f"<{plan.peak_op_type}>"
                            if plan is not None else None),
                "plan_accuracy": (
                    round(rec.plan_accuracy, 4)
                    if rec is not None and rec.plan_accuracy is not None
                    else None),
            },
        }
    finally:
        static.disable_static()
        static.reset_default_programs()
        static.global_scope().clear()


def bench_ir_opt(iters=30):
    """Program-IR optimizer A/B on the three smoke programs.

    For each of the BERT/ResNet/GPT inference smokes (the ir_opt_smoke
    builders: residual+layernorm blocks, conv+bn+relu stages, an int8
    LM head in the ptq residue form) measure planned peak-HBM and
    steady-state µs/step with the optimizer OFF (level 0) vs ON
    (level 1), plus the per-pass rewrite stats (ops_rewritten,
    bytes_saved, wall_ms) the pipeline itself reports. The remat row
    runs the level-2 scenario: an over-budget holding chain whose
    planned peak the rematerializer must cut by >= 20%.
    """
    import importlib.util
    import os

    import paddle_tpu.static as static
    from paddle_tpu import ops
    from paddle_tpu.analysis import optimizer as _iropt
    from paddle_tpu.analysis import plan_memory
    from paddle_tpu.flags import set_flags

    spec = importlib.util.spec_from_file_location(
        "ir_opt_smoke",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "tools", "ir_opt_smoke.py"))
    smoke = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(smoke)

    static.enable_static()
    static.reset_default_programs()
    rows = {}
    try:
        for name, build in (("bert", smoke.build_bert),
                            ("resnet", smoke.build_resnet),
                            ("gpt", smoke.build_gpt)):
            static.global_scope().clear()
            main_p, startup = static.Program(), static.Program()
            with static.program_guard(main_p, startup):
                feeds, fetch = build()
            fetch_name = fetch if isinstance(fetch, str) else fetch.name
            shapes = {k: np.shape(v) for k, v in feeds.items()}
            exe = static.Executor()
            exe.run_startup(startup)

            def _steady(level):
                set_flags({"ir_opt_level": level})
                exe.run(main_p, feed=feeds, fetch_list=[fetch])  # compile
                t0 = time.perf_counter()
                out = None
                for _ in range(iters):
                    out = exe.run(main_p, feed=feeds, fetch_list=[fetch])
                np.asarray(out[0])  # value fetch = barrier
                return (time.perf_counter() - t0) / iters * 1e6

            us_before = _steady(0)
            us_after = _steady(1)
            res = _iropt.optimize_program(main_p, sorted(feeds),
                                          [fetch_name], level=1,
                                          feed_shapes=shapes)
            peak0 = plan_memory(main_p, sorted(feeds), [fetch_name],
                                feed_shapes=shapes).peak_bytes
            peak1 = plan_memory(res.program, sorted(feeds), [fetch_name],
                                feed_shapes=shapes).peak_bytes
            n_fused = sum(
                op.type in ("fused_conv_bn_relu", "fused_layernorm_residual",
                            "matmul_int8", "mul_int8")
                for op in res.program.global_block().ops)
            rows[name] = {
                "peak_bytes_before": int(peak0),
                "peak_bytes_after": int(peak1),
                "us_per_step_before": round(us_before, 1),
                "us_per_step_after": round(us_after, 1),
                "ops_before": len(main_p.global_block().ops),
                "ops_after": len(res.program.global_block().ops),
                "fused_ops": int(n_fused),
                "passes": [dict(name=s.name, ops_rewritten=s.ops_rewritten,
                                bytes_saved=s.bytes_saved,
                                wall_ms=round(s.wall_ms, 3))
                           for s in res.stats],
            }

        # remat scenario: the budget forces level 2 to recompute the
        # held activations; report the planned-peak cut it achieves
        static.global_scope().clear()
        remat_p = static.Program()
        with static.program_guard(remat_p, static.Program()):
            x = static.data("x", [64, 4096], "float32")
            held = [ops.scale(x, scale=float(i + 1)) for i in range(4)]
            acc = ops.relu(held[0])
            for h in held[1:]:
                acc = ops.add(acc, h)
            out = ops.mean(acc)
        shapes = {"x": (64, 4096)}
        budget = 4 * 1024 * 1024 + 256 * 1024
        set_flags({"device_peaks": f"hbm_bytes={budget}"})
        res = _iropt.optimize_program(remat_p, ["x"], [out.name], level=2,
                                      feed_shapes=shapes)
        set_flags({"device_peaks": ""})
        peak0 = plan_memory(remat_p, ["x"], [out.name],
                            feed_shapes=shapes).peak_bytes
        peak2 = plan_memory(res.program, ["x"], [out.name],
                            feed_shapes=shapes).peak_bytes
        rows["remat"] = {
            "budget_bytes": budget,
            "peak_bytes_before": int(peak0),
            "peak_bytes_after": int(peak2),
            "reduction_pct": round(100 * (peak0 - peak2) / peak0, 1),
            "passes": [dict(name=s.name, ops_rewritten=s.ops_rewritten,
                            bytes_saved=s.bytes_saved,
                            wall_ms=round(s.wall_ms, 3))
                       for s in res.stats if s.ops_rewritten],
        }
        return {"metric": "ir_opt", "programs": rows}
    finally:
        set_flags({"ir_opt_level": 1, "device_peaks": ""})
        static.disable_static()
        static.reset_default_programs()
        static.global_scope().clear()


def main():
    import jax

    on_tpu = jax.devices()[0].platform != "cpu"
    result = bench_bert(on_tpu, phase=1)
    result["secondary"] = bench_resnet50(on_tpu)
    # phase-2 at seq 512 exercises the pallas flash-attention kernel on a
    # driver-captured number (dispatch: nn/transformer.py
    # FLASH_ATTENTION_MIN_SEQ)
    result["secondary2"] = bench_bert(on_tpu, phase=2)
    # host-side dispatch health: plan-cache hit rate + donation counters
    result["executor_dispatch"] = bench_executor_dispatch()
    # program-IR optimizer: peak-HBM + µs/step A/B per pass on the
    # BERT/ResNet/GPT smokes, plus the level-2 remat planned-peak cut
    result["ir_opt"] = bench_ir_opt()
    # fused optimizer/layernorm kernels + h2d overlap A/B (ResNet levers)
    result["fused_kernels"] = bench_fused_kernels()
    # always-on span cost with the profiler disabled (target < 2%)
    result["monitor_overhead"] = bench_monitor_overhead()
    # always-on flight-recorder cost, recording on vs off (target < 2%)
    result["flight_recorder_overhead"] = bench_flight_recorder_overhead()
    # per-request trace spans + tail-sampled store, on vs off (target < 2%)
    result["tracing_overhead"] = bench_tracing_overhead()
    # labeled-family observes on the hot path + /fleetz merge (target < 2%)
    result["observability_overhead"] = bench_observability_overhead()
    # goodput-ledger phase transitions on the step path (target < 1%)
    result["goodput_overhead"] = bench_goodput_overhead()
    # per-op stamp cost amortized over a trace epoch (target < 1%) +
    # on-demand replay-profile wall cost, unasserted
    result["opprof_overhead"] = bench_opprof_overhead()
    # online serving: batcher+replicas vs sequential single-request calls
    result["serving_throughput"] = bench_serving_throughput()
    # generative decoding: continuous vs static batching, mixed lengths,
    # speculative draft/verify sub-row (k in {2, 4})
    result["decode_throughput"] = bench_decode_throughput()
    # disaggregated prefill/decode 2-process fleet vs unified, TTFT p99
    result["decode_throughput"]["disagg"] = bench_disagg_fleet()
    # paged KV: shared-prefix sweep (hit rate / FLOPs saved / TTFT),
    # slots-at-equal-HBM on a constrained pool, ring parity
    result["paged_kv"] = bench_paged_kv()
    # serving fleet: 1 -> N backend processes behind the router
    result["router_throughput"] = bench_router_throughput()
    # async snapshot capture on the step path vs blocking saves (target <2%)
    result["checkpoint_overhead"] = bench_checkpoint_overhead()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
