"""Headline benchmark: BERT-base MLM pretraining tokens/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline compares against the A100 GPU-parity target from BASELINE.md
(the reference publishes no numbers in-tree; NVIDIA DeepLearningExamples
BERT-base phase-1 pretraining, seq 128 fp16 + fused kernels, reports
~700-800 sequences/sec on one A100 ≈ 90-100k tokens/sec — we use 90000
tokens/sec/chip as the parity bar).

Recipe parity: phase-1 pretraining at seq 128 with
max_predictions_per_seq=20 — MLM logits are computed only at the gathered
masked positions (BertForPretraining masked_positions path), exactly as the
A100 reference recipe does; dropout (hidden 0.1 + attention 0.1) is ON, as
in the standard config. RNG uses the TPU-native rbg implementation
(framework/random.py) — part of the measured win.

Timing note: the final loss value is fetched (np.asarray), not just
block_until_ready'd — on the remote-TPU (axon) backend block_until_ready
can return before execution completes, giving absurd throughputs; a value
fetch is the reliable barrier.
"""
from __future__ import annotations

import json
import time

import numpy as np

GPU_PARITY_TOKENS_PER_SEC = 90000.0


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.framework import jit as fjit
    from paddle_tpu.models import (
        BertConfig,
        BertForPretraining,
        BertPretrainingCriterion,
    )

    from paddle_tpu import amp

    on_tpu = jax.devices()[0].platform != "cpu"
    # BERT-base with bf16 AMP on TPU (BASELINE.md names "bf16 AMP" as the
    # headline config); batch 128 amortizes the remote-dispatch overhead of
    # the axon backend. Scaled-down config for CPU smoke so bench.py always
    # completes quickly in dev environments.
    if on_tpu:
        cfg = BertConfig()  # base: 12L/768H
        batch, seq, iters = 128, 128, 10
    else:
        cfg = BertConfig(
            vocab_size=8192, hidden_size=256, num_hidden_layers=4,
            num_attention_heads=8, intermediate_size=1024,
            max_position_embeddings=128,
        )
        batch, seq, iters = 8, 128, 3
    n_pred = 20  # max_predictions_per_seq, phase-1 standard

    paddle.seed(0)
    model = BertForPretraining(cfg)
    crit = BertPretrainingCriterion(cfg.vocab_size)
    optimizer = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())

    def loss_fn(m, ids, tt, pos, mlm, nsp):
        with amp.auto_cast():
            pred, rel = m(ids, tt, masked_positions=pos)
        return crit(
            pred.astype("float32"), rel.astype("float32"), mlm, nsp
        )

    step = fjit.train_step(model, optimizer, loss_fn)

    rng = np.random.RandomState(0)
    ids = rng.randint(1, cfg.vocab_size, (batch, seq)).astype("int64")
    tt = rng.randint(0, 2, (batch, seq)).astype("int64")
    # flat positions into the [B*L] hidden-state table, n_pred per sequence
    pos = np.stack(
        [rng.choice(seq, n_pred, replace=False) + i * seq for i in range(batch)]
    ).ravel().astype("int64")
    mlm = rng.randint(0, cfg.vocab_size, (batch * n_pred,)).astype("int64")
    nsp = rng.randint(0, 2, (batch, 1)).astype("int64")

    # warmup + compile
    float(np.asarray(step(ids, tt, pos, mlm, nsp)["loss"]))
    float(np.asarray(step(ids, tt, pos, mlm, nsp)["loss"]))

    t0 = time.perf_counter()
    for _ in range(iters):
        m = step(ids, tt, pos, mlm, nsp)
    float(np.asarray(m["loss"]))  # value fetch = reliable barrier
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt
    print(
        json.dumps(
            {
                "metric": "bert_base_pretrain_tokens_per_sec_per_chip"
                if on_tpu
                else "bert_small_cpu_smoke_tokens_per_sec",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/sec",
                "vs_baseline": round(
                    tokens_per_sec / GPU_PARITY_TOKENS_PER_SEC, 3
                )
                if on_tpu
                else 0.0,
            }
        )
    )


if __name__ == "__main__":
    main()
