"""Classic book-style pipeline with the r5 surfaces: paddle.reader
decorators feeding a model trained with Lookahead(Adam), evaluated
through ExponentialMovingAverage weights.

Run: JAX_PLATFORMS=cpu python examples/reader_ema_training.py
"""
import _bootstrap  # noqa: F401  (repo root onto sys.path)
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
import paddle_tpu.reader as reader


def main():
    paddle.seed(0)
    ds = paddle.text.UCIHousing(mode="train")
    test = paddle.text.UCIHousing(mode="test")

    def raw():
        for i in range(len(ds)):
            yield ds[i]

    pipe = reader.buffered(reader.shuffle(raw, buf_size=128), size=32)

    net = nn.Sequential(nn.Linear(13, 32), nn.ReLU(), nn.Linear(32, 1))
    inner = opt.Adam(learning_rate=2e-2, parameters=net.parameters())
    lookahead = opt.Lookahead(inner, alpha=0.5, k=5)
    ema = opt.ExponentialMovingAverage(parameters=net.parameters(),
                                       decay=0.95)

    def run_epoch():
        batch, losses = [], []
        for sample in pipe():
            batch.append(sample)
            if len(batch) < 32:
                continue
            x = paddle.to_tensor(np.stack([b[0] for b in batch]))
            y = paddle.to_tensor(np.stack([b[1] for b in batch]))
            loss = F.mse_loss(net(x), y)
            loss.backward()
            lookahead.step()
            lookahead.clear_grad()
            ema.update()
            losses.append(float(loss.item()))
            batch = []
        return float(np.mean(losses))

    for epoch in range(40):
        tl = run_epoch()
    xt = paddle.to_tensor(np.stack([test[i][0] for i in range(len(test))]))
    yt = paddle.to_tensor(np.stack([test[i][1] for i in range(len(test))]))
    raw_mse = float(F.mse_loss(net(xt), yt).item())
    with ema.apply():  # evaluate on the smoothed weights
        ema_mse = float(F.mse_loss(net(xt), yt).item())
    print(f"train loss {tl:.4f} | test mse raw {raw_mse:.4f} "
          f"| test mse EMA {ema_mse:.4f}")
    assert tl < 60.0 and np.isfinite(ema_mse)  # prices are ~22.5-scale


if __name__ == "__main__":
    main()
