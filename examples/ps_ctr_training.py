"""CTR-style training with the parameter server + Dataset ingestion.

Demonstrates the two large-scale subsystems working together, single
process for runnability (the multi-process form just moves each role to
its own host — see tests/fixtures/ps_trainer.py):

1. MultiSlot text files → InMemoryDataset (native C++ parser, worker
   fan-out) → global shuffle.
2. A sparse embedding table living on a TableServer (host RAM), pulled/
   pushed per batch by PSEmbedding; the dense head trains on-device.

Run: JAX_PLATFORMS=cpu python examples/ps_ctr_training.py
"""
import _bootstrap  # noqa: F401  (repo root onto sys.path)
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.ps import PSClient, PSEmbedding, ShardedTable, TableServer
from paddle_tpu.io import DatasetFactory


def write_multislot_files(root, n_files=2, rows=64, seed=0):
    """label(1 int) | ids(1-3 sparse ints) | dense(2 floats) per line."""
    rng = np.random.RandomState(seed)
    paths = []
    for fi in range(n_files):
        path = os.path.join(root, f"part-{fi:03d}.txt")
        with open(path, "w") as f:
            for _ in range(rows):
                n_ids = int(rng.randint(1, 4))
                ids = rng.randint(1, 200, n_ids)
                # learnable signal: even-id-heavy rows click
                label = int(ids.sum() % 2 == 0)
                dense = rng.rand(2).round(3)
                f.write(
                    f"1 {label} {n_ids} " + " ".join(map(str, ids))
                    + " 2 " + " ".join(map(str, dense)) + "\n"
                )
        paths.append(path)
    return paths


def main():
    tmp = tempfile.mkdtemp(prefix="ps_ctr_")
    files = write_multislot_files(tmp)

    # -- data: file list -> parsed, shuffled, batched ------------------------
    import paddle_tpu.static as static

    static.enable_static()
    label_v = static.data("click", [-1, 1], "int64")
    ids_v = static.data("slot_ids", [-1, 3], "int64")
    dense_v = static.data("dense_f", [-1, 2], "float32")
    static.disable_static()

    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(16)
    ds.set_thread(2)
    ds.set_filelist(files)
    ds.set_use_var([label_v, ids_v, dense_v])
    ds.load_into_memory()
    ds.set_shuffle_seed(0)
    ds.local_shuffle()
    print("dataset:", ds.desc(), "instances:", ds.get_memory_data_size())

    # -- parameter server: sparse table off-device ---------------------------
    server = TableServer().start()
    table = ShardedTable("ctr_emb", 8, [PSClient(server.endpoint)],
                         init_std=0.05)
    emb = PSEmbedding(table)

    paddle.seed(0)
    head = nn.Sequential(nn.Linear(8 + 2, 16), nn.ReLU(), nn.Linear(16, 2))
    sgd = opt.Adam(learning_rate=0.01, parameters=head.parameters())

    for epoch in range(3):
        losses = []
        for batch in ds._iter_batches():
            label, ids, dense = batch
            e = emb(paddle.to_tensor(ids))          # [B, 3, 8] pulled rows
            feat = paddle.concat(
                [e.sum(axis=1), paddle.to_tensor(dense)], axis=1)
            logits = head(feat)
            loss = F.cross_entropy(
                logits, paddle.to_tensor(label.ravel())).mean()
            loss.backward()
            sgd.step()
            sgd.clear_grad()
            emb.push_step(lr=0.05)                  # sparse grads -> server
            losses.append(float(loss.numpy()))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f}  "
              f"(server rows: {table.clients[0].stats()['ctr_emb']})")

    table.clients[0].shutdown_server()
    print("done")


if __name__ == "__main__":
    main()
