"""BERT MLM pretraining — single chip or sharded mesh.

Usage:
    python examples/train_bert_pretrain.py              # single device
    python examples/train_bert_pretrain.py --dp 2 --tp 4  # 8-chip mesh

On CPU dev boxes: JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8 for a virtual mesh.
"""
import _bootstrap  # noqa: F401  (repo root onto sys.path)
import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu import amp, parallel
from paddle_tpu.framework import jit as fjit
from paddle_tpu.models import (
    BertConfig,
    BertForPretraining,
    BertPretrainingCriterion,
    bert_sharding_rules,
    bert_tiny_config,
)


def synthetic_batch(cfg, batch, seq, n_pred, rng):
    ids = rng.randint(1, cfg.vocab_size, (batch, seq)).astype("int64")
    tt = rng.randint(0, 2, (batch, seq)).astype("int64")
    pos = np.stack(
        [rng.choice(seq, n_pred, replace=False) + i * seq
         for i in range(batch)]
    ).ravel().astype("int64")
    mlm = rng.randint(0, cfg.vocab_size, (batch * n_pred,)).astype("int64")
    nsp = rng.randint(0, 2, (batch, 1)).astype("int64")
    return ids, tt, pos, mlm, nsp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=0, help="data-parallel degree")
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--full", action="store_true",
                    help="BERT-base 12L/768H (default: tiny test config)")
    ns = ap.parse_args()

    cfg = BertConfig() if ns.full else bert_tiny_config()
    paddle.seed(0)
    model = BertForPretraining(cfg)
    crit = BertPretrainingCriterion(cfg.vocab_size)
    optimizer = opt.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())

    def loss_fn(m, ids, tt, pos, mlm, nsp):
        with amp.auto_cast():  # bf16 on the MXU
            pred, rel = m(ids, tt, masked_positions=pos)
        return crit(pred.astype("float32"), rel.astype("float32"), mlm, nsp)

    if ns.dp or ns.tp > 1:
        mesh = parallel.create_mesh(dp=ns.dp or 1, tp=ns.tp)
        step = parallel.sharded_train_step(
            model, optimizer, loss_fn, mesh, rules=bert_sharding_rules()
        )
    else:
        step = fjit.train_step(model, optimizer, loss_fn)

    rng = np.random.RandomState(0)
    for i in range(ns.steps):
        batch = synthetic_batch(cfg, ns.batch, ns.seq, 8, rng)
        loss = float(np.asarray(step(*batch)["loss"]))
        if i % 5 == 0:
            print(f"step {i:4d}  loss {loss:.4f}")
    step.sync()  # device state -> eager model (for save/eval)
    paddle.save(model.state_dict(), "/tmp/bert_example.pdparams")
    print("saved /tmp/bert_example.pdparams")


if __name__ == "__main__":
    main()
