"""DistributedStrategy flags doing real work.

Shows recompute (remat), gradient_merge (k-step accumulation), ZeRO-1
optimizer-state sharding, and LocalSGD — each through the fleet API.

Run on a dev box:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/fleet_strategies.py
"""
import _bootstrap  # noqa: F401  (repo root onto sys.path)
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu import parallel
from paddle_tpu.distributed import fleet


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(32, 64)
        self.fc2 = nn.Linear(64, 8)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def loss_fn(m, x, y):
    return F.cross_entropy(m(x), y).mean()


def run(strategy, label, steps=5):
    paddle.seed(0)
    model = MLP()
    optimizer = opt.Adam(learning_rate=1e-3,
                         parameters=model.parameters())
    fleet.fleet.init(is_collective=True, strategy=strategy)
    dopt = fleet.fleet.distributed_optimizer(optimizer, strategy)
    mesh = parallel.create_mesh(dp=8)
    step = parallel.sharded_train_step(
        model, dopt.inner_opt, loss_fn, mesh,
        strategy=dopt.user_defined_strategy,
    )
    rng = np.random.RandomState(0)
    X = rng.randn(64, 32).astype("float32")
    Y = rng.randint(0, 8, (64,)).astype("int64")
    losses = [float(np.asarray(step(X, Y)["loss"])) for _ in range(steps)]
    print(f"{label:20s} losses {losses[0]:.4f} -> {losses[-1]:.4f}")
    return step


# 1. recompute: forward rematerialized in backward (saves HBM)
s = fleet.DistributedStrategy()
s.recompute = True
run(s, "recompute")

# 2. gradient merge: optimizer applies every k_steps micro-batches
s = fleet.DistributedStrategy()
s.gradient_merge = True
s.gradient_merge_configs.k_steps = 4
run(s, "gradient_merge k=4")

# 3. ZeRO-1: optimizer state sharded over dp
s = fleet.DistributedStrategy()
s.sharding = True
step = run(s, "zero-1 sharding")
acc = step.state["opt"]["accums"]["moment1"][0]
print("   moment1 sharding:", acc.sharding.spec)

# 4. LocalSGD: divergent replicas, periodic param averaging
s = fleet.DistributedStrategy()
s.localsgd = True
s.localsgd_configs.k_steps = 4
run(s, "localsgd k=4")
