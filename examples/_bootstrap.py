"""Shared example bootstrap: make the repo root importable so every
example runs from any cwd (`python examples/foo.py`). The script's own
directory is always on sys.path, so examples just `import _bootstrap`."""
import os
import sys

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _root not in sys.path:
    sys.path.insert(0, _root)
