"""Train → save_inference_model → optimized Predictor → C API.

Run: JAX_PLATFORMS=cpu python examples/inference_deploy.py
"""
import _bootstrap  # noqa: F401  (repo root onto sys.path)
import numpy as np

import paddle_tpu.static as static
from paddle_tpu import ops
from paddle_tpu.inference import Config, create_predictor

static.enable_static()
x = static.data("x", [None, 8], "float32")
y = static.data("y", [None, 1], "float32")
h = static.nn.fc(x, 16, activation="relu")
pred = static.nn.fc(h, 1)
loss = ops.mean(ops.square(ops.subtract(pred, y)))
test_prog = static.default_main_program().clone(for_test=True)
static.optimizer.Adam(learning_rate=0.01).minimize(loss)

exe = static.Executor()
exe.run_startup()
rng = np.random.RandomState(0)
X = rng.randn(256, 8).astype("float32")
W = rng.randn(8, 1).astype("float32")
Y = X @ W
for i in range(100):
    l = exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])[0]
print("final train loss", float(l))

static.save_inference_model("/tmp/lin_model", ["x"], [pred], exe)
static.disable_static()
static.reset_default_programs()
static.global_scope().clear()

cfg = Config("/tmp/lin_model")      # switch_ir_optim on by default:
pred_ = create_predictor(cfg)       # const-fold + DCE run at load
print("pass stats:", pred_.pass_stats)
h_in = pred_.get_input_handle("x")
h_in.copy_from_cpu(X[:4])
pred_.run()
out = pred_.get_output_handle(pred_.get_output_names()[0]).copy_to_cpu()
print("predictions:", out.ravel(), "targets:", Y[:4].ravel())

# the C API builds libpaddle_tpu_capi.so for non-Python hosts:
from paddle_tpu._native.capi import build_capi

print("C API library:", build_capi())
