#!/usr/bin/env Rscript
# MobileNet classification through paddle_tpu inference (the reference's
# r/example/mobilenet.r, ported to the paddle_tpu.inference surface).
# First: python r/example/mobilenet.py /tmp/mobilenet_model

library(reticulate)

inference <- import("paddle_tpu.inference")

set_config <- function() {
    config <- inference$Config("/tmp/mobilenet_model")
    config$switch_ir_optim(TRUE)
    return(config)
}

run_mobilenet <- function() {
    config <- set_config()
    predictor <- inference$create_predictor(config)

    input_names <- predictor$get_input_names()
    input_tensor <- predictor$get_input_handle(input_names[[1]])
    data <- np_array(runif(3 * 224 * 224), dtype = "float32")$reshape(
        as.integer(c(1, 3, 224, 224)))
    input_tensor$copy_from_cpu(data)

    predictor$run()

    output_names <- predictor$get_output_names()
    output_tensor <- predictor$get_output_handle(output_names[[1]])
    output_data <- output_tensor$copy_to_cpu()
    cat("logits shape:", dim(output_data), "\n")
    cat("argmax class:", which.max(output_data) - 1, "\n")
}

if (!interactive()) {
    run_mobilenet()
}
