"""Export a MobileNet inference model for the R demo (the role of the
reference's r/example/mobilenet.py). Run once before mobilenet.r:

    python r/example/mobilenet.py /tmp/mobilenet_model
"""
import sys

import paddle_tpu as paddle
from paddle_tpu.models import MobileNetV1
from paddle_tpu.static import InputSpec


def main(out_dir):
    paddle.seed(0)
    net = MobileNetV1(num_classes=1000)
    net.eval()
    paddle.jit.save(
        net, out_dir,
        input_spec=[InputSpec([None, 3, 224, 224], "float32", name="x")],
    )
    print(f"saved inference model to {out_dir}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/mobilenet_model")
