#!/usr/bin/env python
"""CI smoke for the fused pallas kernels + shared compiled runtime
(`make kernel-smoke`).

Asserts the three contracts the fused-kernel work rests on:

1. **Numeric parity** — the fused momentum/weight-decay update and the
   fused residual+layernorm produce the SAME numbers as the unfused op
   chains, both at the kernel level (pallas interpret mode vs the jnp
   reference, exercising the masked row tails) and through the real
   call sites (Momentum inside a compiled train step, the post-norm
   transformer layer) with the flags flipped.
2. **Zero extra compiles after warmup** — a steady-state compiled train
   loop with the fused kernels on pays exactly ONE executable through
   the shared runtime store (``train_step::exec_cache_miss == 1``, no
   later misses, no cache evictions at the default capacity).
3. **Overlap** — the double-buffered device prefetcher drops the
   monitor's input-wait ratio vs the synchronous refill on the same
   slow source.

Exit 0 on success. Only the overlap check involves timing, with a wide
margin; everything else is exact.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _kernel_parity():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import pallas as _pk  # noqa: F401 (bind modules)

    ou = sys.modules["paddle_tpu.ops.pallas.optimizer_update"]
    lnr = sys.modules["paddle_tpu.ops.pallas.layernorm_residual"]

    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.randn(700, 130).astype("f4"))  # needs padding
    g = jnp.asarray(rng.randn(700, 130).astype("f4"))
    v = jnp.asarray(rng.randn(700, 130).astype("f4"))
    for nesterov in (False, True):
        ref = ou._jnp_update(p, g, v, 0.1, 0.9, 0.01, nesterov)
        out = ou._pallas_update(p, g, v, 0.1, 0.9, 0.01, nesterov,
                                interpret=True)
        for a, b in zip(ref, out):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-6)

    x = jnp.asarray(rng.randn(37, 256).astype("f4"))  # tail tile
    r = jnp.asarray(rng.randn(37, 256).astype("f4"))
    w = jnp.asarray(rng.randn(256).astype("f4"))
    b = jnp.asarray(rng.randn(256).astype("f4"))
    ref = lnr._reference(x, r, w, b, 1e-5)
    y, mean, rstd = lnr._pallas_fwd(x, r, w, b, 1e-5, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(y),
                               rtol=1e-5, atol=1e-5)
    dy = jnp.asarray(rng.randn(37, 256).astype("f4"))
    _, vjp = jax.vjp(lambda x, r, w, b: lnr._reference(x, r, w, b, 1e-5),
                     x, r, w, b)
    refs = vjp(dy)
    da, dw, db = lnr._pallas_bwd(x, r, w, mean, rstd, dy, interpret=True)
    for a, b_ in zip(refs, (da, da, dw, db)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)
    print("kernel parity OK (pallas interpret == jnp reference)")


def _train_parity_and_bounded_compiles():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as popt
    from paddle_tpu import profiler
    from paddle_tpu.flags import set_flags
    from paddle_tpu.framework import jit as fjit

    def losses(fused):
        set_flags({"use_fused_optimizer": fused,
                   "use_fused_layernorm": fused})
        paddle.seed(9)
        net = nn.TransformerEncoderLayer(64, 4, 128, dropout=0.0,
                                         normalize_before=False)
        opt = popt.Momentum(learning_rate=0.02, momentum=0.9,
                            weight_decay=1e-4,
                            parameters=net.parameters())

        def loss_fn(m, x):
            return (m(x) ** 2).mean()

        step = fjit.train_step(net, opt, loss_fn)
        rng = np.random.RandomState(3)
        X = rng.randn(4, 9, 64).astype("f4")
        return [float(np.asarray(step(X)["loss"])) for _ in range(6)]

    try:
        fused = losses(True)
        profiler.reset_counters()
        # steady state with fused kernels: ONE executable, zero evictions
        set_flags({"use_fused_optimizer": True,
                   "use_fused_layernorm": True})
        paddle.seed(9)
        net = nn.Linear(32, 8)
        opt = popt.Momentum(learning_rate=0.05, momentum=0.9,
                            weight_decay=1e-4,
                            parameters=net.parameters())
        step = fjit.train_step(
            net, opt, lambda m, x, y: F.mse_loss(m(x), y).mean())
        rng = np.random.RandomState(1)
        X, Y = rng.randn(8, 32).astype("f4"), rng.randn(8, 8).astype("f4")
        for _ in range(12):
            step(X, Y)
        c = profiler.counters()
        assert c.get("train_step::exec_cache_miss", 0) == 1, c
        assert c.get("train_step::exec_cache_hit", 0) == 11, c
        assert "train_step::cache_evict" not in c, c
        unfused = losses(False)
    finally:
        set_flags({"use_fused_optimizer": True,
                   "use_fused_layernorm": True})
    np.testing.assert_allclose(fused, unfused, rtol=1e-6)
    assert fused[-1] < fused[0], "the fused loop must still train"
    print("train parity OK; warmup = 1 compile, 0 extra, 0 evictions")


def _overlap():
    from paddle_tpu.flags import set_flags
    from paddle_tpu.io.dataloader import _DevicePrefetcher
    from paddle_tpu.monitor import registry as _reg

    def drive(overlap):
        def source():
            for i in range(20):
                time.sleep(0.003)
                yield np.full((8, 8), i, np.float32)

        set_flags({"io_prefetch_overlap": overlap})
        gauge = _reg.gauge("io/input_wait_ms")
        w0 = gauge.value
        t0 = time.perf_counter()
        n = 0
        for _ in _DevicePrefetcher(source(), depth=2, to_device=True):
            time.sleep(0.003)
            n += 1
        wall = time.perf_counter() - t0
        assert n == 20
        return (gauge.value - w0) / (wall * 1e3)

    try:
        ratio_sync = drive(False)
        ratio_overlap = drive(True)
    finally:
        set_flags({"io_prefetch_overlap": True})
    assert ratio_overlap < ratio_sync, (ratio_sync, ratio_overlap)
    print(f"overlap OK: input_wait_ratio {ratio_sync:.3f} -> "
          f"{ratio_overlap:.3f}")


def main():
    _kernel_parity()
    _train_parity_and_bounded_compiles()
    _overlap()
    print("kernel smoke OK")


if __name__ == "__main__":
    main()
