#!/usr/bin/env python
"""Public-API signature dump + compatibility gate.

Reference parity: tools/print_signatures.py + tools/diff_api.py — CI
hashes every public API signature and fails when one changes without an
approved spec update.

Usage:
    python tools/print_signatures.py                 # dump to stdout
    python tools/print_signatures.py --update        # rewrite api_spec.txt
    python tools/print_signatures.py --check         # diff vs api_spec.txt
"""
from __future__ import annotations

import argparse
import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODULES = [
    "paddle_tpu",
    "paddle_tpu.nn",
    "paddle_tpu.nn.functional",
    "paddle_tpu.optimizer",
    "paddle_tpu.optimizer.lr",
    "paddle_tpu.static",
    "paddle_tpu.static.nn",
    "paddle_tpu.tensor",
    "paddle_tpu.linalg",
    "paddle_tpu.distributed",
    "paddle_tpu.distributed.fleet",
    "paddle_tpu.parallel",
    "paddle_tpu.inference",
    "paddle_tpu.metric",
    "paddle_tpu.amp",
    "paddle_tpu.slim",
    "paddle_tpu.io",
    "paddle_tpu.models",
    "paddle_tpu.incubate.auto_checkpoint",
    "paddle_tpu.crypto",
    "paddle_tpu.distributed.elastic",
    "paddle_tpu.distributed.ps",
    "paddle_tpu.text",
    "paddle_tpu.incubate.hapi_text",
    "paddle_tpu.device",
    "paddle_tpu.reader",
    "paddle_tpu.nets",
    "paddle_tpu.runtime",
    "paddle_tpu.generation",
    "paddle_tpu.analysis",
    "paddle_tpu.tuning",
    "paddle_tpu.monitor",
    "paddle_tpu.monitor.goodput",
    "paddle_tpu.monitor.slo",
]

# methods pinned as API surface beyond the module-level names (the spec
# otherwise only sees constructors): (module, class, method)
PINNED_METHODS = [
    ("paddle_tpu.static", "Program", "verify"),
    ("paddle_tpu.static", "Program", "plan_memory"),
    ("paddle_tpu.generation", "GenerationEngine", "suggest_decode_slots"),
    # the paged-KV surface: page-granular handoff + /statz paging block
    ("paddle_tpu.generation", "GenerationEngine", "prefill_export_pages"),
    ("paddle_tpu.generation", "GenerationEngine", "admit_prefilled_pages"),
    ("paddle_tpu.generation", "GenerationEngine", "paging_stats"),
    # the labeled-family API: child metrics per label set
    ("paddle_tpu.monitor", "Counter", "labels"),
    ("paddle_tpu.monitor", "Gauge", "labels"),
    ("paddle_tpu.monitor", "Histogram", "labels"),
    ("paddle_tpu.monitor", "Histogram", "series"),
]


def collect():
    import importlib

    lines = []
    for mod_name in MODULES:
        mod = importlib.import_module(mod_name)
        # __all__ is the module's declared public surface — incidental
        # imports must not get pinned as API
        public = getattr(mod, "__all__", None)
        for name in sorted(public) if public is not None else sorted(dir(mod)):
            if name.startswith("_"):
                continue
            obj = getattr(mod, name)
            if inspect.ismodule(obj):
                continue
            try:
                if inspect.isclass(obj) or callable(obj):
                    try:
                        sig = str(inspect.signature(obj))
                    except (ValueError, TypeError):
                        sig = "(...)"
                    lines.append(f"{mod_name}.{name}{sig}")
            except Exception:
                continue
    for mod_name, cls_name, meth_name in PINNED_METHODS:
        mod = importlib.import_module(mod_name)
        meth = getattr(getattr(mod, cls_name), meth_name)
        try:
            sig = str(inspect.signature(meth))
        except (ValueError, TypeError):
            sig = "(...)"
        lines.append(f"{mod_name}.{cls_name}.{meth_name}{sig}")
    return sorted(set(lines))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--update", action="store_true")
    ap.add_argument("--check", action="store_true")
    ns = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    lines = collect()
    spec_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "api_spec.txt")
    if ns.update:
        with open(spec_path, "w") as f:
            f.write("\n".join(lines) + "\n")
        print(f"wrote {len(lines)} signatures to {spec_path}")
        return 0
    if ns.check:
        if not os.path.exists(spec_path):
            print("no api_spec.txt; run --update first")
            return 1
        with open(spec_path) as f:
            old = {l.strip() for l in f if l.strip()}
        new = set(lines)
        removed = sorted(old - new)
        added = sorted(new - old)
        for l in removed:
            print("REMOVED", l)
        for l in added:
            print("ADDED  ", l)
        if removed:
            print(f"FAIL: {len(removed)} public APIs changed/removed "
                  "(update tools/api_spec.txt if intended)")
            return 1
        print(f"OK ({len(new)} signatures, {len(added)} new)")
        return 0
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
