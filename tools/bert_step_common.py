"""Shared BERT-base train-step construction for the perf diagnostics
(tools/profile_bert.py and tools/bert_dots.py must measure the SAME
program as bench.py's headline recipe)."""
from __future__ import annotations

import numpy as np


def build_bert_step(batch=128, seq=128, n_pred=20, device_put=False):
    """Returns (step, batch_args) — the bench.py phase-1 recipe."""
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import amp
    from paddle_tpu.framework import jit as fjit
    from paddle_tpu.models import (
        BertConfig, BertForPretraining, BertPretrainingCriterion,
    )

    cfg = BertConfig(use_flash_attention=True)
    paddle.seed(0)
    model = BertForPretraining(cfg)
    crit = BertPretrainingCriterion(cfg.vocab_size)
    optimizer = opt.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())

    def loss_fn(m, ids, tt, pos, mlm, nsp):
        with amp.auto_cast():
            pred, rel = m(ids, tt, masked_positions=pos)
        return crit(pred.astype("float32"), rel.astype("float32"), mlm, nsp)

    step = fjit.train_step(model, optimizer, loss_fn)
    rng = np.random.RandomState(0)
    args = (
        rng.randint(1, cfg.vocab_size, (batch, seq)).astype("int64"),
        rng.randint(0, 2, (batch, seq)).astype("int64"),
        np.stack([
            rng.choice(seq, n_pred, replace=False) + i * seq
            for i in range(batch)
        ]).ravel().astype("int64"),
        rng.randint(0, cfg.vocab_size, (batch * n_pred,)).astype("int64"),
        rng.randint(0, 2, (batch, 1)).astype("int64"),
    )
    if device_put:
        args = tuple(jax.device_put(a) for a in args)
    return step, args
