"""ResNet-50 single-chip layout/batch sweep (VERDICT r3 item 1 evidence).

Runs the exact bench.py train-step recipe over a grid of
(data_format, batch, amp) and prints one JSON line per config.
Usage: python tools/sweep_resnet.py [--configs NCHW:128 NHWC:128 ...]
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def run(data_format: str, batch: int, iters: int = 20, size: int = 224,
        use_amp: bool = True, recompute: bool = False):
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu import amp
    from paddle_tpu.framework import jit as fjit
    from paddle_tpu.models import resnet50

    paddle.seed(0)
    model = resnet50(num_classes=1000, data_format=data_format)
    optimizer = opt.Momentum(
        learning_rate=0.1, momentum=0.9, parameters=model.parameters()
    )

    def loss_fn(m, x, y):
        if use_amp:
            with amp.auto_cast():
                logits = m(x)
        else:
            logits = m(x)
        return F.cross_entropy(logits.astype("float32"), y).mean()

    step = fjit.train_step(model, optimizer, loss_fn, recompute=recompute)
    rng = np.random.RandomState(0)
    shape = (batch, 3, size, size) if data_format == "NCHW" else (batch, size, size, 3)
    x = jax.device_put(rng.randn(*shape).astype("float32"))
    y = jax.device_put(rng.randint(0, 1000, (batch,)).astype("int64"))

    t_c0 = time.perf_counter()
    l0 = float(np.asarray(step(x, y)["loss"]))
    compile_s = time.perf_counter() - t_c0
    float(np.asarray(step(x, y)["loss"]))
    t0 = time.perf_counter()
    for _ in range(iters):
        m = step(x, y)
    l1 = float(np.asarray(m["loss"]))
    dt = time.perf_counter() - t0
    ips = batch * iters / dt
    return {
        "data_format": data_format, "batch": batch, "amp": use_amp,
        "remat": recompute,
        "images_per_sec": round(ips, 1), "compile_s": round(compile_s, 1),
        "loss_start": round(l0, 4), "loss_end": round(l1, 4),
        "vs_2500": round(ips / 2500.0, 3),
    }


def main():
    configs = sys.argv[1:] or ["NCHW:128", "NHWC:128", "NHWC:256", "NCHW:256"]
    for c in configs:
        parts = c.split(":")
        df, b = parts[0], int(parts[1])
        use_amp = len(parts) < 3 or "noamp" not in parts[2:]
        recompute = "remat" in parts[2:]
        try:
            r = run(df, b, use_amp=use_amp, recompute=recompute)
        except Exception as e:  # keep sweeping on OOM etc.
            r = {"data_format": df, "batch": b, "error": str(e)[:200]}
        print(json.dumps(r), flush=True)


if __name__ == "__main__":
    main()
