#!/usr/bin/env python
"""CI smoke for the fleet SLO plane (`make slo-smoke`).

Boots the real fleet shape — two backend processes behind a Router —
with one latency SLO installed fleet-wide via ``FLAGS_slo_objectives``
in the children's env, wedges ONE backend (a huge --batch-timeout-ms
holds every request far past the SLO threshold), and asserts the
error-budget contracts end to end:

- the wedged backend's ``/sloz`` shows both window burns past the alert
  threshold with ``alerting=true`` and a ``slo_burn`` flight event; the
  healthy backend's burn stays at zero;
- ``/metricz`` serves prometheus text with the labeled per-kind series,
  and ``/metricz?format=snapshot`` the JSON registry snapshot;
- router ``/fleetz`` p50/p99 for ``serving/e2e_ms`` exactly equal a
  hand-merge of the two backends' own snapshots (the fleet view IS the
  pooled histogram);
- a router-local SLO over ``serving/router_e2e_ms`` pushes its
  confirmed burn through ``FleetSignals.slo_burn`` and the autoscaler
  reads it as up-pressure even though queues are shallow.

Exit 0 on success; a failure is a real SLO-plane regression.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from urllib.request import Request, urlopen

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

IN_DIM = 16
THRESHOLD_MS = 50.0
WEDGE_TIMEOUT_MS = 400.0
REQUESTS = 12
OBJECTIVE = ("predict-fast|serving/e2e_ms{kind=predict}"
             f"|threshold_ms={THRESHOLD_MS}|target=0.99|window_s=120")


def _build_model_dir():
    import paddle_tpu.static as static

    static.enable_static()
    static.reset_default_programs()
    static.global_scope().clear()
    try:
        x = static.data("x", [None, IN_DIM], "float32")
        y = static.nn.fc(static.nn.fc(x, 64, name="ssm_fc1"), 8,
                         name="ssm_fc2")
        exe = static.Executor()
        exe.run_startup()
        d = tempfile.mkdtemp(prefix="ptpu_slo_smoke_")
        static.save_inference_model(d, ["x"], [y], exe)
    finally:
        static.disable_static()
        static.reset_default_programs()
    return d


def _get(url, timeout=10):
    with urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


def _post_predict(url, rows, timeout=30):
    a = np.random.RandomState(rows).randn(rows, IN_DIM).astype("float32")
    body = json.dumps({"inputs": a.tolist(),
                       "tenant": "smoke"}).encode()
    with urlopen(Request(url + "/predict", data=body,
                         headers={"Content-Type": "application/json"}),
                 timeout=timeout) as r:
        return r.status


def main():
    from paddle_tpu import monitor
    from paddle_tpu.monitor import flight_recorder as _flight
    from paddle_tpu.monitor import slo as slo_mod
    from paddle_tpu.serving import Router
    from paddle_tpu.serving.scaler import AutoScaler, launch_process

    model_dir = _build_model_dir()
    env = {"FLAGS_slo_objectives": OBJECTIVE,
           "FLAGS_slo_sample_interval_s": "0.2",
           "JAX_PLATFORMS": "cpu"}
    print("booting 1 healthy + 1 wedged backend process ...", flush=True)
    common = ["--model-dir", model_dir, "--port", "0",
              "--buckets", "1,2,4", "--queue-capacity", "256"]
    healthy = launch_process(
        "paddle_tpu.serving.backend",
        common + ["--batch-timeout-ms", "1"], env=env)
    # the wedge: every request waits out the batch window, far past the
    # 50ms SLO threshold — slow-but-answering, so /healthz stays green
    # and only the SLO plane sees the violation
    wedged = launch_process(
        "paddle_tpu.serving.backend",
        common + ["--batch-timeout-ms", str(WEDGE_TIMEOUT_MS)], env=env)
    backends = [healthy, wedged]
    router = Router(backends=[b.url for b in backends],
                    probe_interval_s=0.2).start()
    try:
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and router.healthy_count < 2:
            time.sleep(0.05)
        assert router.healthy_count == 2, router.healthz()

        # router-local objective over the router's own e2e histogram,
        # sampled manually around the burst (deterministic windows)
        slo_mod.reset_engine()
        rslo = slo_mod.install_slo(slo_mod.SLO(
            "router-fast", "serving/router_e2e_ms",
            threshold_ms=THRESHOLD_MS, target=0.99, window_s=120.0))
        slo_mod.engine().sample()

        # -- traffic. Sequential requests all tie at score 0 and P2C
        # tie-breaks by URL, so force a phase with ONLY the wedged
        # backend in rotation — the router e2e histogram must contain
        # threshold-busting requests deterministically, not by port
        # order luck. Direct posts give each backend's own /sloz a
        # guaranteed share too.
        for i in range(REQUESTS):
            assert _post_predict(router.url, rows=(i % 3) + 1) == 200
        router.remove_backend(healthy.url)
        for i in range(6):
            assert _post_predict(router.url, rows=1) == 200
        router.add_backend(healthy.url)
        for b in backends:
            for i in range(4):
                assert _post_predict(b.url, rows=1) == 200
        slo_mod.engine().sample()
        print(f"traffic done: {REQUESTS} mixed + 6 wedge-only via "
              "router, 4 direct per backend", flush=True)

        # -- /metricz both modes on a live backend ---------------------
        status, ctype, raw = _get(healthy.url + "/metricz")
        assert status == 200 and ctype.startswith("text/plain"), ctype
        assert b'serving_e2e_ms_bucket{' in raw, (
            "labeled series missing from prometheus text")
        assert b'kind="predict"' in raw and b'tenant="smoke"' in raw
        status, ctype, raw = _get(healthy.url +
                                  "/metricz?format=snapshot")
        assert status == 200 and "json" in ctype, ctype
        assert "serving/e2e_ms" in json.loads(raw)["metrics"]
        print("/metricz OK: prometheus text with kind/tenant labels + "
              "JSON snapshot mode", flush=True)

        # -- /sloz: wedged burns past alert, healthy does not ----------
        wz = hz = None
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            wz = json.loads(_get(wedged.url + "/sloz")[2])["slos"][0]
            hz = json.loads(_get(healthy.url + "/sloz")[2])["slos"][0]
            if wz["alerting"] and hz["samples"] >= 2:
                break
            time.sleep(0.2)
        assert wz["name"] == "predict-fast" and wz["samples"] >= 2, wz
        assert wz["alerting"], (
            "wedged backend never crossed the alert burn", wz)
        assert wz["burn"]["fast"] >= wz["alert_burn"], wz
        assert wz["burn"]["slow"] >= wz["alert_burn"], wz
        assert not hz["alerting"], (
            "healthy backend must not page", hz)
        assert (hz["burn"]["fast"] or 0.0) < wz["alert_burn"], hz
        # the router-local objective crossed alert too (>= 6 wedge-only
        # requests of <= 22 against a 1% budget): its transition left a
        # slo_burn flight event in THIS process's recorder
        burns = [e for e in _flight.events()
                 if e.get("kind") == "slo_burn"]
        assert burns and burns[-1]["slo"] == "router-fast", (
            "slo_burn flight event missing for the router-local SLO")
        print(f"/sloz OK: wedged burn fast={wz['burn']['fast']}x "
              f"slow={wz['burn']['slow']}x >= alert "
              f"{wz['alert_burn']}x; healthy fast="
              f"{hz['burn']['fast']}x; router-local slo_burn flight "
              "event recorded", flush=True)

        # -- /fleetz == hand-merged golden -----------------------------
        name = "serving/e2e_ms"
        snaps = [json.loads(_get(b.url + "/metricz?format=snapshot")[2])
                 ["metrics"] for b in backends]
        golden = monitor.merge_histogram_snapshots(
            [s[name] for s in snaps], name=name)
        fz = row = None
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            fz = json.loads(_get(router.url + "/fleetz")[2])
            row = fz["fleet"].get("predict", {}).get(name)
            if row and row["count"] == golden.count:
                break
            time.sleep(0.1)
        assert row is not None and fz["backends_scraped"] == 2, fz
        assert row["count"] == golden.count, (row, golden.count)
        for q, key in ((0.5, "p50_ms"), (0.99, "p99_ms")):
            want = round(monitor.histogram_quantile(golden, q), 3)
            assert row[key] == want, (key, row[key], want)
        assert row["series"], "labeled series missing from /fleetz"
        print(f"/fleetz OK: fleet p50={row['p50_ms']}ms "
              f"p99={row['p99_ms']}ms over {row['count']} requests == "
              "hand-merged golden, labeled series attached", flush=True)

        # -- the scaler sees the burn ----------------------------------
        burn = slo_mod.current_burn()
        assert burn > 0.0, "router-local SLO produced no confirmed burn"
        sc = AutoScaler(router, launcher=None, min_backends=1,
                        max_backends=4, up_queue_depth=1e9,
                        down_queue_depth=-1.0, window=2,
                        cooldown_s=0.0, interval_s=60.0)
        try:
            sig = sc.signals()
            assert sig.slo_burn == burn, (sig.slo_burn, burn)
            if burn >= sc.burn_alert:
                assert sc.decide(sig) is None  # hysteresis tick 1
                assert sc.decide(sig) == "up", (
                    "confirmed burn past alert must be up-pressure")
                verdict = "decide()=up"
            else:
                verdict = "below alert (no page), signal plumbed"
        finally:
            sc.stop(drain=False)
        print(f"scaler OK: FleetSignals.slo_burn={round(burn, 2)}x "
              f"(alert {sc.burn_alert}x), {verdict}", flush=True)

        print("slo-smoke OK: labeled /metricz, burn-rate paging on the "
              "wedged backend only, /fleetz == pooled golden, scaler "
              "sees the burn")
        return 0
    finally:
        slo_mod.reset_engine()
        try:
            router.stop(drain=False)
        except Exception:
            pass
        for b in backends:
            try:
                b.proc.kill()
                b.proc.wait(10)
            except Exception:
                pass


if __name__ == "__main__":
    sys.exit(main())
