"""Attribute f32 vs bf16 dots in the BERT step HLO by op_name metadata."""
from __future__ import annotations

import collections
import re

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import amp
    from paddle_tpu.framework import jit as fjit
    from paddle_tpu.models import (
        BertConfig, BertForPretraining, BertPretrainingCriterion,
    )

    cfg = BertConfig(use_flash_attention=True)
    batch, seq, n_pred = 128, 128, 20
    paddle.seed(0)
    model = BertForPretraining(cfg)
    crit = BertPretrainingCriterion(cfg.vocab_size)
    optimizer = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())

    def loss_fn(m, ids, tt, pos, mlm, nsp):
        with amp.auto_cast():
            pred, rel = m(ids, tt, masked_positions=pos)
        return crit(pred.astype("float32"), rel.astype("float32"), mlm, nsp)

    step = fjit.train_step(model, optimizer, loss_fn)
    rng = np.random.RandomState(0)
    ids = rng.randint(1, cfg.vocab_size, (batch, seq)).astype("int64")
    tt = rng.randint(0, 2, (batch, seq)).astype("int64")
    pos = np.stack(
        [rng.choice(seq, n_pred, replace=False) + i * seq
         for i in range(batch)]).ravel().astype("int64")
    mlm = rng.randint(0, cfg.vocab_size, (batch * n_pred,)).astype("int64")
    nsp = rng.randint(0, 2, (batch, 1)).astype("int64")

    lr = jax.numpy.asarray(1e-4, jax.numpy.float32)
    key = jax.random.PRNGKey(0)
    # use the STABLEHLO (pre-optimization) text: metadata survives there
    lowered = jax.jit(step.pure).lower(
        step.state, (ids, tt, pos, mlm, nsp), lr, key)
    txt = lowered.as_text()
    agg = collections.Counter()
    for line in txt.splitlines():
        if "dot_general" not in line:
            continue
        dt = "f32" if re.search(r"->\s*tensor<[^>]*f32>", line) else (
            "bf16" if re.search(r"->\s*tensor<[^>]*bf16>", line) else "?")
        nm = re.search(r'loc\("([^"]*)"', line)
        name = nm.group(1) if nm else "?"
        # compress the op_name path to its most telling component
        short = "/".join(p for p in name.split("/") if p)[:110]
        agg[(dt, short)] += 1
    by_dtype = collections.Counter()
    for (dt, name), c in agg.items():
        by_dtype[dt] += c
    print(dict(by_dtype))
    for (dt, name), c in sorted(agg.items()):
        if dt == "f32":
            print(f"f32 x{c}  {name}")


if __name__ == "__main__":
    main()
