"""Attribute f32 vs bf16 dots in the BERT step HLO (the census that
caught the missing-"linear" AMP white-list entry, see COVERAGE.md)."""
from __future__ import annotations

import collections
import re
import os
import sys


def main():
    import jax

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.bert_step_common import build_bert_step

    step, args = build_bert_step()
    lr = jax.numpy.asarray(1e-4, jax.numpy.float32)
    key = jax.random.PRNGKey(0)
    lowered = jax.jit(step.pure).lower(step.state, args, lr, key)
    try:  # debug_info carries loc("...") source attribution per op
        txt = lowered.as_text(debug_info=True)
    except TypeError:
        txt = lowered.as_text()
    agg = collections.Counter()
    by_site = collections.Counter()
    for line in txt.splitlines():
        if "dot_general" not in line:
            continue
        dt = "f32" if re.search(r"->\s*tensor<[^>]*f32>", line) else (
            "bf16" if re.search(r"->\s*tensor<[^>]*bf16>", line) else "?")
        agg[dt] += 1
        if dt == "f32":
            nm = re.search(r'loc\("([^"]+)"', line)
            by_site[(nm.group(1) if nm else "?")[:110]] += 1
    print(dict(agg))
    # the attribution that caught the missing-"linear" white-list entry:
    # every f32 dot named by its source site
    for site, c in by_site.most_common():
        print(f"f32 x{c}  {site}")


if __name__ == "__main__":
    main()
