"""Attribute f32 vs bf16 dots in the BERT step HLO (the census that
caught the missing-"linear" AMP white-list entry, see COVERAGE.md)."""
from __future__ import annotations

import collections
import re
import sys


def main():
    import jax

    sys.path.insert(0, ".")
    from tools.bert_step_common import build_bert_step

    step, args = build_bert_step()
    lr = jax.numpy.asarray(1e-4, jax.numpy.float32)
    key = jax.random.PRNGKey(0)
    txt = jax.jit(step.pure).lower(step.state, args, lr, key).as_text()
    agg = collections.Counter()
    for line in txt.splitlines():
        if "dot_general" not in line:
            continue
        dt = "f32" if re.search(r"->\s*tensor<[^>]*f32>", line) else (
            "bf16" if re.search(r"->\s*tensor<[^>]*bf16>", line) else "?")
        agg[dt] += 1
    print(dict(agg))


if __name__ == "__main__":
    main()
