#!/usr/bin/env python
"""CI smoke for the hardware-utilization accounting stack (`make mfu-smoke`).

Drives both compile paths that feed the cost model — a static-graph
Executor train loop and a framework/jit compiled train step — then
asserts:
- a CostRecord was captured on each path from XLA's real
  cost_analysis()/memory_analysis() (FLOPs > 0, not an estimate), and a
  pure-matmul jit matches the 2·M·N·K hand count;
- the TrainingMonitor line carries ``mfu=``/``hbm_bw_util=``/
  ``roofline=`` computed from the executed-work ledger;
- ``/costz`` and ``/clusterz`` render on the debug server, and
  ``/metrics`` serves the cost gauges under the Prometheus content type.

Exit 0 on success; nothing here depends on timing — a failure is a real
regression in the utilization-accounting path.
"""
from __future__ import annotations

import json
import os
import sys
from urllib.request import urlopen

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu.static as static
    from paddle_tpu import monitor, ops
    from paddle_tpu.monitor import cost_model, debug_server

    # -- matmul golden: XLA's FLOPs must match the hand count ----------
    M, K, N = 128, 256, 64

    def matmul(a, b):
        return a @ b

    lowered = jax.jit(matmul).lower(
        jnp.zeros((M, K), jnp.float32), jnp.zeros((K, N), jnp.float32))
    rec = cost_model.capture("smoke_matmul", lowered=lowered,
                             compiled=lowered.compile())
    want = 2.0 * M * N * K
    assert rec.flops and abs(rec.flops - want) / want < 0.05, \
        (rec.flops, want)

    static.enable_static()
    static.reset_default_programs()
    static.global_scope().clear()
    srv = debug_server.DebugServer(port=0).start()
    try:
        # -- executor path under the monitor ---------------------------
        x = static.data("x", [8, 16], "float32")
        y = static.data("y", [8, 1], "float32")
        w = static.nn.create_parameter([16, 1], "float32")
        loss = ops.mean(ops.square(ops.subtract(ops.matmul(x, w), y)))
        opt = static.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
        exe = static.Executor()
        exe.run_startup()
        rng = np.random.RandomState(0)
        X = rng.randn(8, 16).astype("float32")
        Y = rng.randn(8, 1).astype("float32")

        mon = monitor.TrainingMonitor("mfu_smoke", interval=100)
        for _ in range(3):
            with mon.step(examples=8):
                exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])
        # 3 steps < interval: close() must flush the partial window
        line = mon.close()
        assert line, "close() flushed no partial-window line"
        for field in ("mfu=", "hbm_bw_util=", "roofline="):
            assert field in line, (field, line)

        exec_rec = cost_model.latest_record("executor")
        assert exec_rec is not None and exec_rec.flops > 0, exec_rec
        assert exec_rec.runs == 3, exec_rec.runs
        ledger = monitor.registry_snapshot()["cost/executed_flops"]["value"]
        assert abs(ledger - 3 * exec_rec.flops) < 1e-6 * ledger + 1.0

        # -- compiled-train-step path ----------------------------------
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as popt
        from paddle_tpu.framework import jit as fjit

        paddle.seed(0)
        net = nn.Linear(16, 4)
        optimizer = popt.SGD(learning_rate=0.1,
                             parameters=net.parameters())

        def loss_fn(m, a, b):
            return ((m(a) - b) ** 2).mean()

        step = fjit.train_step(net, optimizer, loss_fn)
        a = rng.randn(8, 16).astype("float32")
        b = rng.randn(8, 4).astype("float32")
        for _ in range(2):
            step(a, b)
        jit_rec = cost_model.latest_record("train_step")
        assert jit_rec is not None and jit_rec.flops > 0, jit_rec
        assert jit_rec.runs == 2, jit_rec.runs

        # -- debug endpoints -------------------------------------------
        costz = json.loads(urlopen(srv.url + "/costz").read())
        labels = {r["label"] for r in costz["records"]}
        assert {"executor", "train_step", "smoke_matmul"} <= labels, labels
        assert costz["device_peaks"]["flops"] > 0
        assert costz["executed_flops"] > 0

        clusterz = json.loads(urlopen(srv.url + "/clusterz").read())
        assert len(clusterz["ranks"]) == 1  # single-process world view
        assert "mfu" in clusterz["ranks"][0]
        assert clusterz["stragglers"] == []

        resp = urlopen(srv.url + "/metrics")
        ctype = resp.headers.get("Content-Type", "")
        assert ctype.startswith("text/plain; version=0.0.4"), ctype
        prom = resp.read().decode()
        for series in ("cost_executed_flops", "cost_executor_flops",
                       "monitor_mfu_smoke_mfu"):
            assert series in prom, series

        print(f"mfu-smoke OK: executor {exec_rec.flops:.0f} FLOPs/step, "
              f"train_step {jit_rec.flops:.0f} FLOPs/step, "
              f"matmul golden {rec.flops:.0f}=={want:.0f}, "
              f"monitor line: {line}")
        return 0
    finally:
        srv.stop()
        static.disable_static()
        static.reset_default_programs()
        static.global_scope().clear()


if __name__ == "__main__":
    sys.exit(main())
