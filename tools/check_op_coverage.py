#!/usr/bin/env python
"""Op-coverage checker (tools/check_op_desc.py / check_op_register_type.py
role): compares the live kernel registry against the reference's
REGISTER_OPERATOR list (tools/reference_ops.txt, extracted from
paddle/fluid/operators) and reports covered / missing / extra ops.

Exit code 1 if coverage drops below --min-pct.

Usage: python tools/check_op_coverage.py [--min-pct 55] [--show-missing]
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# grad ops, infrastructure ops and backends the TPU runtime absorbs by
# design (XLA fusion/comm/memory) — excluded from the coverage target
ABSORBED_PREFIXES = (
    "c_",           # collectives: mesh axes + lax collectives
    "fusion_", "fused_",  # XLA fuses
    "graph_",
    "listen_and_serv", "send", "recv", "fetch_barrier", "send_barrier",
    "gen_nccl_id", "ncclinit", "nccl",
    "checkpoint_notify", "fl_listen",
    "lookup_sparse_table", "distributed_lookup",
    "tensorrt_engine", "anakin_engine",
    "quantize", "dequantize", "requantize",  # mkldnn int8 backend ops
    "go", "channel_",  # CSP ops removed upstream too
)
ABSORBED = {
    "while", "conditional_block", "recurrent",  # control flow: we expose
    "read_from_array", "write_to_array",        # while/cond/scan instead
    "select_input", "select_output",            # cond plumbing
    "create_double_buffer_reader", "create_py_reader", "read",
    "double_buffer", "py_reader",
    "allreduce", "broadcast",  # distributed.collective API
    "ref_by_trainer_id", "get_tensor_from_selected_rows",
    "merge_selected_rows", "clip_by_norm",  # SelectedRows machinery
    "split_ids", "merge_ids", "split_byref", "split_selected_rows",
    "beam_search", "beam_search_decode",  # ops.beam_search module
    "warpctc",  # vendor library kernel
    # LoD machinery: the ragged design is padded+lengths / flat+segment
    # ids (ops/sequence.py) — these conversion ops have no meaning there
    "array_to_lod_tensor", "lod_tensor_to_array", "lod_reset",
    "merge_lod_tensor", "split_lod_tensor", "shrink_rnn_memory",
    "lod_array_length", "lod_rank_table", "reorder_lod_tensor_by_rank",
    # io ops: serialization is the python save/load layer
    # (framework/serialization.py, static/io.py)
    "save", "save_combine", "load", "load_combine", "delete_var",
    "run_program",  # the Executor compiles blocks directly
    "coalesce_tensor",  # gradient fusion is XLA's job
    # vendor-fused kernels: capability covered by nn.rnn / static.nn
    # lstm/gru over scan; no cudnn to bind
    "cudnn_lstm", "attention_lstm", "lstm", "lstmp_fused",
    # backend engines
    "lite_engine", "anakin_engine",
    # parameter-server sparse-table ops (PS runtime deferred, SURVEY §7)
    "pull_sparse", "pull_sparse_v2", "push_sparse", "push_sparse_v2",
    "pull_box_sparse", "push_box_sparse", "push_box_extended_sparse",
    # sync_batch_norm: under GSPMD a dp-sharded batch mean IS the global
    # mean — XLA inserts the cross-replica psum the reference hand-wrote
    "sync_batch_norm", "inplace_abn",
}


def load_reference(path):
    with open(path) as f:
        return {l.strip() for l in f if l.strip()}


# kernel-name renames (registry name != reference op type)
KNOWN_RENAMES = {
    "momentum": "momentum_update", "adam": "adam_update",
    "adamax": "adamax_update", "adagrad": "adagrad_update",
    "adadelta": "adadelta_update", "rmsprop": "rmsprop_update",
    "ftrl": "ftrl_update", "lamb": "lamb_update",
    "lars_momentum": "lars_momentum_update", "dpsgd": "dpsgd_update",
    "gaussian_random": "gaussian_random", "uniform_random": "uniform",
}


def classify(ref_ops, registered, api_names):
    covered, missing, absorbed = set(), set(), set()
    for op in ref_ops:
        if op.endswith("_grad") or op.endswith("_grad2"):
            # the reference registers every gradient as its own op
            # (457 forward + grads); here jax.vjp synthesizes them —
            # absorbed by the autodiff design, not missing capability
            absorbed.add(op)
        elif op in registered or KNOWN_RENAMES.get(op) in registered:
            covered.add(op)
        elif op in api_names:
            covered.add(op)  # exposed under the same public API name
        elif op.startswith(ABSORBED_PREFIXES) or op in ABSORBED:
            absorbed.add(op)
        else:
            missing.add(op)
    extra = registered - ref_ops
    return covered, missing, absorbed, extra


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-pct", type=float, default=90.0)
    ap.add_argument("--show-missing", action="store_true")
    ns = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu  # noqa: F401  (registers kernels)
    from paddle_tpu import nn, ops
    from paddle_tpu.ops.registry import all_ops

    here = os.path.dirname(os.path.abspath(__file__))
    ref = load_reference(os.path.join(here, "reference_ops.txt"))
    registered = set(all_ops())
    api_names = {n for n in dir(ops) if not n.startswith("_")}
    api_names |= {n.lower() for n in dir(nn) if not n.startswith("_")}
    api_names |= {
        n for n in dir(nn.functional) if not n.startswith("_")
    }
    covered, missing, absorbed, extra = classify(ref, registered, api_names)
    target = len(ref) - len(absorbed)
    pct = 100.0 * len(covered) / max(target, 1)
    print(f"reference ops:      {len(ref)}")
    print(f"absorbed-by-design: {len(absorbed)}")
    print(f"coverage target:    {target}")
    print(f"covered:            {len(covered)}  ({pct:.1f}%)")
    print(f"missing:            {len(missing)}")
    print(f"tpu-native extras:  {len(extra)}")
    if ns.show_missing:
        for op in sorted(missing):
            print("  MISSING", op)
    if pct < ns.min_pct:
        print(f"FAIL: coverage {pct:.1f}% < {ns.min_pct}%")
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
