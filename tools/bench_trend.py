"""Bench trend check: compare the two newest BENCH_r*.json and warn on
>20% regressions of headline rows.

Each BENCH_r*.json (written by the growth driver around ``bench.py``)
has the shape ``{"n", "cmd", "rc", "tail", "parsed"}`` where ``parsed``
nests headline rows — dicts carrying a ``metric`` name and a numeric
``value`` (throughput: higher is better) — at arbitrary depth
(``secondary``, ``executor_dispatch``, ...). This tool walks both
trees, pairs rows by metric name, and reports the delta.

Exit status is 0 even when regressions are found (a trend WARNING, not
a gate) unless ``--strict`` is passed, so CI can surface drift without
flaking on noisy CPU runners.

Usage::

    python tools/bench_trend.py [--dir REPO] [--threshold 0.20] [--strict]
"""
import argparse
import glob
import json
import os
import re
import sys

_NUM_RE = re.compile(r"BENCH_r(\d+)\.json$")


def find_latest_pair(directory):
    """Return (older_path, newer_path) of the two highest-numbered
    BENCH_r*.json, or None if fewer than two exist."""
    runs = []
    for path in glob.glob(os.path.join(directory, "BENCH_r*.json")):
        m = _NUM_RE.search(os.path.basename(path))
        if m:
            runs.append((int(m.group(1)), path))
    runs.sort()
    if len(runs) < 2:
        return None
    return runs[-2][1], runs[-1][1]


def headline_rows(parsed):
    """Flatten ``parsed`` into {metric_name: value} over every nested
    dict that carries a ``metric`` name and a numeric ``value``."""
    rows = {}

    def walk(node):
        if not isinstance(node, dict):
            return
        name = node.get("metric")
        val = node.get("value")
        if isinstance(name, str) and isinstance(val, (int, float)):
            rows.setdefault(name, float(val))
        for v in node.values():
            walk(v)

    walk(parsed)
    return rows


def lower_is_better(name):
    """Overhead/latency-style rows regress UPWARD; throughput rows
    regress downward."""
    n = name.lower()
    return ("overhead" in n or n.endswith("_pct") or n.endswith("_ms")
            or n.endswith("_us") or "latency" in n)


def compare(old, new, threshold=0.20):
    """Return (report_lines, regressions) comparing two parsed trees."""
    old_rows = headline_rows(old.get("parsed") or {})
    new_rows = headline_rows(new.get("parsed") or {})
    lines, regressions = [], []
    for name in sorted(set(old_rows) | set(new_rows)):
        if name not in old_rows:
            lines.append(f"  NEW      {name} = {new_rows[name]:g}")
            continue
        if name not in new_rows:
            lines.append(f"  DROPPED  {name} (was {old_rows[name]:g})")
            regressions.append((name, old_rows[name], None))
            continue
        o, n = old_rows[name], new_rows[name]
        if o <= 0:
            lines.append(f"  SKIP     {name}: non-positive baseline {o:g}")
            continue
        delta = (n - o) / o
        worse = delta >= threshold if lower_is_better(name) \
            else delta <= -threshold
        better = delta <= -threshold if lower_is_better(name) \
            else delta >= threshold
        tag = "ok"
        if worse:
            tag = "REGRESSED"
            regressions.append((name, o, n))
        elif better:
            tag = "improved"
        lines.append(f"  {tag:<9}{name}: {o:g} -> {n:g} ({delta:+.1%})")
    return lines, regressions


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--threshold", type=float, default=0.20,
                    help="relative drop that counts as a regression")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when regressions are found")
    args = ap.parse_args(argv)

    pair = find_latest_pair(args.dir)
    if pair is None:
        print("[bench-trend] fewer than two BENCH_r*.json runs; "
              "nothing to compare")
        return 0
    old_path, new_path = pair
    with open(old_path) as f:
        old = json.load(f)
    with open(new_path) as f:
        new = json.load(f)
    print(f"[bench-trend] {os.path.basename(old_path)} -> "
          f"{os.path.basename(new_path)} "
          f"(threshold {args.threshold:.0%})")
    lines, regressions = compare(old, new, args.threshold)
    for line in lines:
        print(line)
    if regressions:
        for name, o, n in regressions:
            where = "dropped" if n is None else f"{o:g} -> {n:g}"
            print(f"[bench-trend] WARNING: {name} regressed "
                  f">{args.threshold:.0%} ({where})")
        return 1 if args.strict else 0
    print("[bench-trend] no headline regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
