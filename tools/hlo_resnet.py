"""Dump compiled-HLO statistics for the ResNet-50 train step (gap evidence)."""
from __future__ import annotations

import collections
import json
import re
import sys

import numpy as np


def main():
    import jax

    sys.path.insert(0, ".")
    from tools.sweep_resnet import run  # noqa: F401 (reuse build pieces)
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu import amp
    from paddle_tpu.framework import jit as fjit
    from paddle_tpu.models import resnet50

    data_format = sys.argv[1] if len(sys.argv) > 1 else "NCHW"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 128

    paddle.seed(0)
    model = resnet50(num_classes=1000, data_format=data_format)
    optimizer = opt.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())

    def loss_fn(m, x, y):
        with amp.auto_cast():
            logits = m(x)
        return F.cross_entropy(logits.astype("float32"), y).mean()

    step = fjit.train_step(model, optimizer, loss_fn)
    rng = np.random.RandomState(0)
    shape = (batch, 3, 224, 224) if data_format == "NCHW" else (batch, 224, 224, 3)
    x = jax.device_put(rng.randn(*shape).astype("float32"))
    y = jax.device_put(rng.randint(0, 1000, (batch,)).astype("int64"))

    lr = jax.numpy.asarray(0.1, jax.numpy.float32)
    rng = jax.random.PRNGKey(0)
    lowered = jax.jit(step.pure).lower(step.state, (x._array if hasattr(x, "_array") else x,
                                                    y._array if hasattr(y, "_array") else y), lr, rng)
    compiled = lowered.compile()
    txt = compiled.as_text()
    convs = collections.Counter()
    for m in re.finditer(r"(\S+) = (\S+) convolution\(", txt):
        convs[m.group(2).split("[")[0]] += 1
    dots = collections.Counter()
    for m in re.finditer(r"(\S+) = (\S+) dot\(", txt):
        dots[m.group(2).split("[")[0]] += 1
    # shared cost-analysis normalization/guard: monitor.cost_model
    from paddle_tpu.monitor import cost_model

    ca = cost_model.analyze_cost(compiled) or {}
    flops = ca.get("flops", 0)
    bytes_ = ca.get("bytes accessed", 0)
    print(json.dumps({
        "conv_out_dtypes": dict(convs),
        "dot_out_dtypes": dict(dots),
        "flops_G": round(flops / 1e9, 1),
        "bytes_GB": round(bytes_ / 1e9, 2),
        "flops_per_image_G": round(flops / 1e9 / batch, 2),
    }))


if __name__ == "__main__":
    main()
