#!/usr/bin/env python
"""CI smoke for the observability stack (`make trace-smoke`).

Runs a 3-step static-graph train under the profiler + TrainingMonitor,
exports BOTH telemetry formats, and asserts:
- the merged chrome trace is non-empty valid JSON with executor spans,
- the Prometheus dump renders and contains the step histogram,
- the monitor emitted its periodic line with every documented field.

Exit 0 on success; any assertion failing the smoke is a real regression
in the telemetry path, not flake — nothing here depends on timing.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    import paddle_tpu as paddle
    import paddle_tpu.static as static
    from paddle_tpu import monitor, ops, profiler

    static.enable_static()
    static.reset_default_programs()
    static.global_scope().clear()
    try:
        x = static.data("x", [8, 4], "float32")
        y = static.data("y", [8, 1], "float32")
        w = static.nn.create_parameter([4, 1], "float32")
        loss = ops.mean(ops.square(ops.subtract(ops.matmul(x, w), y)))
        opt = static.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
        exe = static.Executor()
        exe.run_startup()

        rng = np.random.RandomState(0)
        X = rng.randn(8, 4).astype("float32")
        Y = rng.randn(8, 1).astype("float32")

        profiler.reset_profiler()
        profiler.start_profiler(state="CPU")
        mon = monitor.TrainingMonitor("smoke", interval=3)
        for _ in range(3):
            with mon.step(examples=8):
                exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])
        profiler.stop_profiler()

        out_dir = tempfile.mkdtemp(prefix="ptpu_trace_smoke_")
        trace_path = os.path.join(out_dir, "merged_trace.json")
        prom_path = os.path.join(out_dir, "metrics.prom")
        monitor.export_merged_chrome_trace(trace_path)
        monitor.export_prometheus(prom_path)

        with open(trace_path) as f:
            trace = json.load(f)
        events = trace["traceEvents"]
        names = {e.get("name") for e in events}
        assert events, "merged chrome trace has no events"
        assert any(str(n).startswith("executor::") for n in names), names
        assert any(str(n).startswith("monitor::") for n in names), names

        prom = open(prom_path).read()
        assert "# TYPE" in prom and "monitor_smoke_step_ms_bucket" in prom

        line = mon.last_line
        assert line and "step=3" in line, line
        for field in ("step_ms=", "examples_per_sec=", "input_wait_ratio=",
                      "plan_cache_hit_rate=", "jit_cache_hit_rate=",
                      "hbm_peak_bytes="):
            assert field in line, (field, line)

        print(f"trace-smoke OK: {len(events)} trace events, "
              f"{len(prom.splitlines())} prometheus lines -> {out_dir}")
        return 0
    finally:
        static.disable_static()
        static.reset_default_programs()
        static.global_scope().clear()


if __name__ == "__main__":
    sys.exit(main())
