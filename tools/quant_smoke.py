#!/usr/bin/env python
"""CI smoke for quantization end-to-end (`make quant-smoke`).

Asserts the contracts the int8 work rests on, one per leg:

1. **Kernel parity** — the pallas int8 matmul (interpret mode) is
   BIT-equal to the jnp int8 dot_general fallback, including padded
   tails on every axis (integer math: `FLAGS_use_int8_matmul` may never
   change numerics).
2. **Deployable int8 serving** — PTQ → ``save_int8_model`` → an
   UNCHANGED Predictor inside a real ``InferenceServer``: HTTP answers
   match the fp32 program within the documented envelope, the saved
   params really are int8, and the bounded-compile discipline holds
   (warmup == len(buckets) jit misses, zero unexpected after a mixed
   burst — the int8 program compiles through the same CompiledStore).
3. **int8 KV cache** — the int8-KV engine decodes the same greedy
   tokens as the fp32 engine on the same weights, fits ≥ 1.8× the
   decode slots in equal HBM (measured on the real cache arrays), and
   stays compile-bound (zero extra compiles after warmup).
4. **Quantized all-reduce** — the gradient-sync wire bytes certified
   from the collective ledger itself (≥ 3.5× cut under a dp-8 mesh),
   and BERT-smoke loss-curve convergence with the int8 gradient sync
   within tolerance of fp32.

Exit 0 on success. Nothing here depends on wall-clock timing.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
from urllib.request import Request, urlopen

# 4's dp-8 ledger trace needs forced host devices BEFORE jax imports
os.environ.setdefault(
    "XLA_FLAGS",
    (os.environ.get("XLA_FLAGS", "")
     + " --xla_force_host_platform_device_count=8").strip())

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _kernel_parity():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.int8_matmul import (
        _jnp_matmul,
        _pallas_matmul,
    )

    rng = np.random.RandomState(0)
    for m, k, n in [(32, 128, 128), (37, 70, 130), (300, 129, 257)]:
        x = jnp.asarray(rng.randint(-127, 128, (m, k)).astype(np.int8))
        w = jnp.asarray(rng.randint(-127, 128, (k, n)).astype(np.int8))
        ref = np.asarray(_jnp_matmul(x, w))
        got = np.asarray(_pallas_matmul(x, w, interpret=True))
        assert (got == ref).all(), f"int8 kernel parity broke at {m,k,n}"
    print("quant-smoke: int8 matmul pallas-interpret == jnp (bit-equal, "
          "padded tails included)")


def _int8_serving():
    import paddle_tpu.static as static
    from paddle_tpu import profiler, slim
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.serving import InferenceServer

    buckets = (1, 2, 4)
    rng = np.random.RandomState(4)
    static.enable_static()
    static.reset_default_programs()
    static.global_scope().clear()
    try:
        x = static.data("x", [None, 16], "float32")
        h = static.nn.fc(x, 64, activation="relu", name="qs1")
        y = static.nn.fc(h, 8, name="qs2")
        exe = static.Executor()
        exe.run_startup()
        prog = static.default_main_program()
        calib = [{"x": rng.randn(16, 16).astype("float32")}
                 for _ in range(4)]
        tests = [rng.randn(r, 16).astype("float32") for r in (1, 2, 3, 1)]
        refs = [np.asarray(exe.run(feed={"x": a}, fetch_list=[y])[0])
                for a in tests]
        ptq = slim.PostTrainingQuantization(exe, prog, calib)
        ptq.quantize()
        model_dir = tempfile.mkdtemp(prefix="ptpu_quant_smoke_")
        ptq.save_int8_model(model_dir, ["x"], [y])
    finally:
        static.disable_static()
        static.reset_default_programs()
        static.global_scope().clear()

    meta = slim.load_quant_metadata(model_dir)
    assert meta and meta["int8_weights"], "int8 weights missing from meta"

    pred = create_predictor(Config(model_dir))
    types = [op.type for op in pred._program.global_block().ops]
    assert "mul_int8" in types, types
    srv = InferenceServer(pred, port=0, replicas=2, buckets=buckets,
                          batch_timeout_ms=1.0)
    try:
        misses0 = profiler.counters().get("executor::jit_cache_miss", 0)
        srv.start()  # warms every bucket
        warm = (profiler.counters().get("executor::jit_cache_miss", 0)
                - misses0)
        assert warm == len(buckets), (
            f"int8 program warmup cost {warm} compiles, expected "
            f"{len(buckets)} — one per bucket through the CompiledStore")
        fp32_scale = max(np.abs(r).max() for r in refs)
        for a, ref in zip(tests, refs):
            body = json.dumps({"inputs": a.tolist()}).encode()
            r = urlopen(Request(
                srv.url + "/predict", data=body,
                headers={"Content-Type": "application/json"}))
            assert r.status == 200
            out = json.loads(r.read())
            got = np.asarray(next(iter(out["outputs"].values())),
                             dtype="float32")
            err = np.abs(got - ref).max()
            assert err < 0.05 * fp32_scale + 0.05, (
                f"int8 serving answer off fp32 by {err} (envelope 5%)")
        total = (profiler.counters().get("executor::jit_cache_miss", 0)
                 - misses0)
        assert total == len(buckets) and srv.pool.extra_compiles() == 0, (
            "mixed int8 traffic must add ZERO compiles after warmup")
    finally:
        srv.stop(drain=True)
    print(f"quant-smoke: int8 InferenceServer parity OK "
          f"({len(buckets)} compiles exactly, 0 unexpected; "
          f"int8 weights: {meta['int8_weights']})")


def _int8_kv_cache():
    import paddle_tpu as paddle
    from paddle_tpu.generation import GenerationEngine
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny_config

    paddle.seed(3)
    cfg = gpt_tiny_config()
    cfg.attention_window = 16
    model = GPTForCausalLM(cfg)
    model.eval()
    prompts = [[5, 9, 4], [7, 3], [11, 2, 8, 6]]
    eng32 = GenerationEngine(model, slots=2, cache_len=16,
                             prefill_buckets=(4, 8), seed=2).warmup()
    ref = eng32.generate(prompts, max_new_tokens=12, temperature=0.0)
    eng8 = GenerationEngine(model, slots=2, cache_len=16,
                            prefill_buckets=(4, 8), kv_cache_dtype="int8",
                            seed=2).warmup()
    got = eng8.generate(prompts, max_new_tokens=12, temperature=0.0)
    assert got == ref, (
        f"int8 KV decode diverged from fp32 greedy tokens: {got} != {ref}")
    assert eng8.extra_compiles() == 0, "int8 decode must stay compile-bound"
    ratio = eng32.cache_nbytes() / eng8.cache_nbytes()
    assert ratio >= 1.8, (
        f"int8 KV cache packs only {ratio:.2f}x the slots per HBM byte; "
        "needs >= 1.8x")
    print(f"quant-smoke: int8 KV decode == fp32 greedy tokens, "
          f"{ratio:.2f}x slots at equal HBM, 0 extra compiles")


def _quantized_allreduce():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import monitor, parallel
    from paddle_tpu.distributed import quantized as qar
    from paddle_tpu.framework import jit as fjit
    from paddle_tpu.models import (
        BertConfig,
        BertForPretraining,
        BertPretrainingCriterion,
    )

    # -- ledger wire-byte cut under a dp-8 mesh ------------------------
    mesh = parallel.create_mesh(dp=8)
    g = jnp.ones((4096, 64), jnp.float32)
    with parallel.mesh_scope(mesh):
        s0 = monitor.registry_snapshot()
        try:
            jax.make_jaxpr(
                lambda a: qar.sync_grads({"w": a}, quantized=False))(g)
        except Exception:
            pass  # psum needs a bound axis; accounting already fired
        s1 = monitor.registry_snapshot()
        jax.make_jaxpr(
            lambda a: qar.sync_grads({"w": a}, quantized=True))(g)
        s2 = monitor.registry_snapshot()
    fp32_bytes = qar.wire_bytes_per_step(s0, s1)
    int8_bytes = qar.wire_bytes_per_step(s1, s2)
    cut = fp32_bytes / int8_bytes
    assert cut >= 3.5, (
        f"quantized all-reduce cuts wire bytes only {cut:.2f}x "
        f"({fp32_bytes} -> {int8_bytes}); needs >= 3.5x")

    # -- BERT smoke: loss-curve convergence vs fp32 --------------------
    cfg = BertConfig(
        vocab_size=2048, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=64)
    rng = np.random.RandomState(0)
    batch, seq, n_pred, steps = 4, 32, 4, 8
    ids = rng.randint(1, cfg.vocab_size, (batch, seq)).astype("int64")
    tt = rng.randint(0, 2, (batch, seq)).astype("int64")
    pos = np.stack([rng.choice(seq, n_pred, replace=False) + i * seq
                    for i in range(batch)]).reshape(-1).astype("int64")
    mlm = rng.randint(1, cfg.vocab_size, (batch * n_pred,)).astype("int64")
    nsp = rng.randint(0, 2, (batch,)).astype("int64")

    def run(flag_on):
        paddle.set_flags({"quantized_allreduce": flag_on})
        try:
            paddle.seed(1)
            model = BertForPretraining(cfg)
            crit = BertPretrainingCriterion(cfg.vocab_size)
            o = opt.AdamW(learning_rate=5e-4,
                          parameters=model.parameters())
            step = fjit.train_step(
                model, o,
                lambda m, i, t, p, ml, ns: crit(
                    *m(i, t, masked_positions=p), ml, ns))
            return [float(np.asarray(step(ids, tt, pos, mlm, nsp)["loss"]))
                    for _ in range(steps)]
        finally:
            paddle.set_flags({"quantized_allreduce": False})

    fp = run(False)
    q = run(True)
    assert q[-1] < q[0], f"int8-sync BERT loss did not descend: {q}"
    delta = max(abs(a - b) for a, b in zip(fp, q))
    assert delta < 0.05, (
        f"int8-sync BERT loss curve drifted {delta:.4f} from fp32 "
        f"(tolerance 0.05)\n  fp32: {fp}\n  int8: {q}")
    print(f"quant-smoke: all-reduce wire bytes cut {cut:.2f}x "
          f"({fp32_bytes} -> {int8_bytes}); BERT loss curve within "
          f"{delta:.4f} of fp32 over {steps} steps")


def main():
    _kernel_parity()
    _int8_serving()
    _int8_kv_cache()
    _quantized_allreduce()
    print("quant-smoke: OK")


if __name__ == "__main__":
    main()
