#!/usr/bin/env python
"""IR-optimizer smoke (ISSUE 16): the program-IR optimizer, certified.

Optimizes BERT-, ResNet-, and GPT-shaped static inference programs and
checks, end to end through ``Executor.run``:

1. **Fusion fires** — at ``FLAGS_ir_opt_level=1`` every smoke program
   contains at least one fused registry op after optimization
   (``fused_conv_bn_relu`` on ResNet, ``fused_layernorm_residual`` on
   BERT/GPT, ``matmul_int8`` on the GPT int8 head) and fewer ops than
   it started with;
2. **Numeric goldens** — the optimized programs produce the same
   fetches as the unoptimized ones (bit-exact for the f32 fusions,
   tight allclose for the int8 contraction whose accumulation order
   legitimately differs);
3. **Training byte-identity** — a training program (``grad::`` ops
   present) is returned UNCHANGED at level 1: same object, same bytes;
4. **Rematerialization admits** — a deliberately over-budget program
   that ``FLAGS_memory_budget_check=strict`` rejects at level 1 is
   admitted at level 2, with the planned peak reduced by >= 20%.

Run: ``make ir-opt-smoke`` (wired into ``tools/build_and_test.sh check``).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

MB = 1024 * 1024


def _check(name, ok, detail=""):
    status = "ok" if ok else "FAIL"
    print(f"[ir-opt-smoke] {name}: {status} {detail}")
    if not ok:
        raise SystemExit(f"ir-opt smoke failed: {name} {detail}")


def build_bert():
    """BERT-shaped inference: embedding + residual-add->layer_norm
    encoder blocks + MLM head."""
    import paddle_tpu.static as static
    from paddle_tpu import ops

    B, S, E, V = 8, 16, 32, 128
    ids = static.data("ids", [B, S], "int64")
    table = static.nn.create_parameter([V, E], "float32")
    h = ops.reshape(ops.embedding(ids, table), [B * S, E])
    for i in range(2):
        ff = static.nn.fc(h, E, activation="relu", name=f"enc{i}")
        h = static.nn.layer_norm(ops.add(ff, h))
    logits = static.nn.fc(h, V, name="mlm")
    rng = np.random.RandomState(0)
    feeds = {"ids": rng.randint(0, V, (B, S)).astype("int64")}
    return feeds, logits


def build_resnet():
    """ResNet-shaped inference: two conv->bn->relu stages + fc head."""
    import paddle_tpu.static as static
    from paddle_tpu import ops

    B = 4
    img = static.data("img", [B, 3, 16, 16], "float32")
    h = static.nn.conv2d(img, num_filters=8, filter_size=3, padding=1,
                         bias_attr=False, name="c1")
    h = ops.relu(static.nn.batch_norm(h, is_test=True))
    h = static.nn.conv2d(h, num_filters=16, filter_size=3, padding=1,
                         bias_attr=False, name="c2")
    h = ops.relu(static.nn.batch_norm(h, is_test=True))
    h = ops.max_pool2d(h, 2, stride=2)
    logits = static.nn.fc(h, 10, name="head")
    rng = np.random.RandomState(1)
    feeds = {"img": rng.randn(B, 3, 16, 16).astype("float32")}
    return feeds, logits


def build_gpt():
    """GPT-shaped inference: fc decoder stack with residual layernorms
    plus an int8 LM head in the ``ptq.rewrite_int8_program`` residue
    form (qdq'd activation, ``dequantize_static``'d int8 weight)."""
    import paddle_tpu.static as static
    from paddle_tpu import ops

    B, S, E, V = 4, 16, 32, 128
    ids = static.data("ids", [B, S], "int64")
    table = static.nn.create_parameter([V, E], "float32")
    h = ops.reshape(ops.embedding(ids, table), [B * S, E])
    for i in range(2):
        ff = static.nn.fc(h, E, activation="relu", name=f"blk{i}")
        h = static.nn.layer_norm(ops.add(ff, h))

    # int8 LM head, hand-lowered to the deploy-time residue the slim
    # pipeline leaves for ops without a direct int8 path: the weight
    # ships as a scope-resident int8 array restored by a load-time
    # dequantize_static, the activation keeps its fake-quant sim op
    block = static.default_main_program().global_block()
    rng = np.random.RandomState(2)
    w = rng.randn(E, V).astype("float32")
    w_scale = float(np.max(np.abs(w)))
    w_int8 = np.clip(np.round(w / w_scale * 127.0), -127, 127).astype("int8")
    act_scale = 8.0  # covers the layernormed activations comfortably
    block.create_var(name="head_w@int8", shape=[E, V], dtype="int8",
                     persistable=True)
    static.global_scope().set("head_w@int8", w_int8)
    block.create_var(name="head_w@deq", shape=[E, V], dtype="float32")
    block.append_op("dequantize_static", {"X": ["head_w@int8"]},
                    {"Out": ["head_w@deq"]},
                    {"scale": w_scale, "bit_length": 8, "dtype": "float32"})
    block.create_var(name=f"{h.name}@qdq", shape=[B * S, E], dtype="float32")
    block.append_op("quant_dequant_static", {"X": [h.name]},
                    {"Out": [f"{h.name}@qdq"]},
                    {"scale": act_scale, "bit_length": 8})
    block.create_var(name="gpt_logits", shape=[B * S, V], dtype="float32")
    block.append_op("matmul", {"X": [f"{h.name}@qdq", "head_w@deq"]},
                    {"Out": ["gpt_logits"]}, {})
    feeds = {"ids": rng.randint(0, V, (B, S)).astype("int64")}
    return feeds, "gpt_logits"


_EXPECT_FUSED = {
    "bert": ("fused_layernorm_residual",),
    "resnet": ("fused_conv_bn_relu",),
    "gpt": ("fused_layernorm_residual", "matmul_int8"),
}

# the int8 contraction accumulates in int32 and dequantizes once, so it
# is not bit-identical to the f32 matmul of the dequantized grid
_TOL = {"bert": 0.0, "resnet": 0.0, "gpt": 1e-4}


def _run_smoke(name, build):
    import paddle_tpu.static as static
    from paddle_tpu.analysis import optimizer as iropt
    from paddle_tpu.flags import set_flags

    static.global_scope().clear()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        feeds, fetch = build()
    exe = static.Executor()
    exe.run_startup(startup)

    set_flags({"ir_opt_level": 0})
    golden = np.asarray(exe.run(main, feed=feeds, fetch_list=[fetch])[0])
    set_flags({"ir_opt_level": 1})
    got = np.asarray(exe.run(main, feed=feeds, fetch_list=[fetch])[0])

    fetch_name = fetch if isinstance(fetch, str) else fetch.name
    res = iropt.optimize_program(
        main, sorted(feeds), [fetch_name], level=1,
        feed_shapes={k: np.shape(v) for k, v in feeds.items()})
    before = len(main.global_block().ops)
    after_ops = [op.type for op in res.program.global_block().ops]
    counts = {t: after_ops.count(t) for t in _EXPECT_FUSED[name]}
    _check(f"{name} fusion fires", res.changed and all(
        c > 0 for c in counts.values()),
        f"(ops {before}->{len(after_ops)}, fused {counts})")

    tol = _TOL[name]
    diff = float(np.max(np.abs(golden - got)))
    denom = float(np.max(np.abs(golden))) or 1.0
    ok = diff == 0.0 if tol == 0.0 else diff / denom <= tol
    _check(f"{name} numerically golden", ok,
           f"(max abs diff {diff:.3g}, tol {tol})")


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu import ops
    from paddle_tpu.analysis import MemoryBudgetError, plan_memory
    from paddle_tpu.analysis import optimizer as iropt
    from paddle_tpu.flags import set_flags

    static.enable_static()

    # 1+2) fusion fires and stays numerically golden on all three
    for name, build in (("bert", build_bert), ("resnet", build_resnet),
                        ("gpt", build_gpt)):
        _run_smoke(name, build)

    # 3) a training program is byte-identical at level 1
    static.global_scope().clear()
    main_p, startup = static.Program(), static.Program()
    with static.program_guard(main_p, startup):
        feeds, logits = build_bert()
        label = static.data("label", [8 * 16, 1], "int64")
        loss = ops.mean(ops.softmax_with_cross_entropy(logits, label))
        static.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    before = main_p.serialize_to_string()
    res = iropt.optimize_program(main_p, sorted(feeds) + ["label"],
                                 [loss.name], level=1)
    _check("training program byte-identical at level 1",
           (not res.changed) and res.program is main_p
           and main_p.serialize_to_string() == before,
           f"({sum(op.type.startswith('grad::') for op in main_p.global_block().ops)} grad ops kept)")

    # 4) remat: strict-rejected at level 1, admitted at level 2
    static.global_scope().clear()
    remat_p = static.Program()
    with static.program_guard(remat_p, static.Program()):
        x = static.data("x", [64, 4096], "float32")  # 1 MiB
        held = [ops.scale(x, scale=float(i + 1)) for i in range(4)]
        acc = ops.relu(held[0])
        for h in held[1:]:
            acc = ops.add(acc, h)
        out = ops.mean(acc)
    feeds = {"x": np.random.RandomState(3).randn(64, 4096).astype("float32")}
    budget = 4 * MB + 256 * 1024
    set_flags({"device_peaks": f"hbm_bytes={budget}",
               "memory_budget_check": "strict", "ir_opt_level": 1})
    exe = static.Executor()
    try:
        exe.run(remat_p, feed=feeds, fetch_list=[out])
        _check("strict rejects over-budget program at level 1", False)
    except MemoryBudgetError as e:
        _check("strict rejects over-budget program at level 1", True,
               f"(peak {e.peak_bytes / MB:.1f}MiB > {budget / MB:.2f}MiB)")
    set_flags({"ir_opt_level": 2})
    admitted = np.asarray(exe.run(remat_p, feed=feeds, fetch_list=[out])[0])
    set_flags({"device_peaks": "", "memory_budget_check": "warn",
               "ir_opt_level": 0})
    golden = np.asarray(exe.run(remat_p, feed=feeds, fetch_list=[out])[0])
    _check("remat admits under strict budget",
           float(np.max(np.abs(golden - admitted))) == 0.0,
           f"(result {float(admitted):.6f}, bit-exact)")

    set_flags({"device_peaks": f"hbm_bytes={budget}"})
    shapes = {"x": (64, 4096)}
    res = iropt.optimize_program(remat_p, ["x"], [out.name], level=2,
                                 feed_shapes=shapes)
    p0 = plan_memory(remat_p, ["x"], [out.name], feed_shapes=shapes).peak_bytes
    p2 = plan_memory(res.program, ["x"], [out.name],
                     feed_shapes=shapes).peak_bytes
    set_flags({"device_peaks": ""})
    _check("remat peak reduction >= 20%", (p0 - p2) / p0 >= 0.20,
           f"({p0 / MB:.1f}MiB -> {p2 / MB:.1f}MiB, "
           f"-{100 * (p0 - p2) / p0:.0f}%)")

    stats = iropt.optimizer_stats()
    _check("per-pass stats recorded",
           all(stats.get(p, {}).get("ops_rewritten", 0) > 0
               for p in ("fuse_conv_bn_relu", "fuse_layernorm_residual",
                         "fuse_int8_matmul", "rematerialize")),
           f"({ {k: v['ops_rewritten'] for k, v in stats.items()} })")

    print("[ir-opt-smoke] PASS")


if __name__ == "__main__":
    main()
