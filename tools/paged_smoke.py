#!/usr/bin/env python
"""CI smoke for the paged KV subsystem (`make paged-smoke`).

Four production contracts, end to end on the tiny GPT:

1. **Ring-vs-paged greedy parity at bounded compiles**: a mixed burst
   of 8 prompts produces EXACTLY the ring engine's greedy tokens on
   the paged layout (fp32), warmup costs exactly len(prefill ladder)
   + 1 programs, and the burst afterwards compiles NOTHING — the
   unified full/suffix prefill is one program per bucket no matter how
   much prefix is shared.
2. **90%-shared-prefix burst**: requests repeating a long templated
   prefix admit through the radix index — prefill FLOPs drop by the
   shared fraction (suffix bucket vs full bucket) and measured TTFT
   (admit wall time) drops with them.
3. **Slots at equal HBM**: a mixed short/long burst runs
   token-identically on a pool 1.6x smaller than the ring's 4-slot
   reservation — equivalently, >= 1.3x the slots in the same cache
   bytes (the paged layout's capacity claim).
4. **Strict memplan admission**: an over-budget page pool is refused
   at ENGINE CONSTRUCTION (before any device allocation), naming the
   slot count that would fit.

Exit 0 on success; a failure is a real paging regression.
"""
from __future__ import annotations

import os
import statistics
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

CACHE = 64
PS = 4
BUCKETS = (8, 64)


def main():
    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.analysis import MemoryBudgetError
    from paddle_tpu.flags import set_flags
    from paddle_tpu.generation import COMPILE_COUNTER, GenerationEngine
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny_config

    paddle.seed(13)
    cfg = gpt_tiny_config()
    cfg.attention_window = CACHE
    model = GPTForCausalLM(cfg)
    model.eval()

    def ring(**kw):
        return GenerationEngine(model, slots=4, cache_len=CACHE,
                                prefill_buckets=BUCKETS, seed=5, **kw)

    def paged(**kw):
        return GenerationEngine(model, slots=4, cache_len=CACHE,
                                prefill_buckets=BUCKETS, seed=5,
                                kv_cache_layout="paged",
                                kv_page_size=PS, **kw)

    # -- 1: ring-vs-paged parity x8 at bounded compiles ----------------
    rng = np.random.RandomState(0)
    prompts = [list(map(int, rng.randint(3, 200, size=n)))
               for n in (1, 3, 8, 5, 2, 7, 4, 6)]
    ref_eng = ring().warmup()
    want = ref_eng.generate(prompts, max_new_tokens=10, temperature=0.0)
    eng = paged()
    c0 = profiler.counters().get(COMPILE_COUNTER, 0)
    eng.warmup()
    warm = profiler.counters().get(COMPILE_COUNTER, 0) - c0
    assert warm == len(BUCKETS) + 1, (
        f"paged warmup cost {warm} compiles, expected prefill ladder "
        f"({len(BUCKETS)}) + decode")
    got = eng.generate(prompts, max_new_tokens=10, temperature=0.0)
    assert got == want, "paged layout diverged from the ring goldens"
    total = profiler.counters().get(COMPILE_COUNTER, 0) - c0
    assert total == len(BUCKETS) + 1 and eng.extra_compiles() == 0, (
        f"burst grew compiles to {total}; the unified full/suffix "
        "prefill must stay compile-once per bucket")

    # -- 2: 90%-shared-prefix burst: FLOPs saved + TTFT drop -----------
    shared = list(map(int, rng.randint(3, 200, size=56)))  # 14 pages
    burst = [shared + list(map(int, rng.randint(3, 200, size=8)))
             for _ in range(9)]  # 64 tokens, 87.5% shared
    reuse = paged().warmup()

    def admit_times(engine, reqs):
        ts = []
        for r in reqs:
            t0 = time.perf_counter()
            engine.admit(0, r, 0.0)
            ts.append(time.perf_counter() - t0)
            engine.release_slot(0)
        return ts

    cold = admit_times(reuse, burst[:1])  # populates the index
    warm_ts = admit_times(reuse, burst[1:])
    st = reuse.paging_stats()
    assert st["prefix_index"]["hits"] == len(burst) - 1, st
    # FLOPs saved: the reused admits prefill the 8-token suffix bucket
    # instead of the full 64-token bucket
    flops_saved = 1.0 - BUCKETS[0] / BUCKETS[-1]
    assert flops_saved >= 0.85, flops_saved
    ttft_full = cold[0]
    ttft_reused = statistics.median(warm_ts)
    assert ttft_reused < ttft_full, (
        f"shared-prefix TTFT {ttft_reused * 1e3:.2f}ms did not drop "
        f"below the cold full-prefill {ttft_full * 1e3:.2f}ms")
    assert reuse.extra_compiles() == 0, (
        "suffix prefill recompiled; shared_len must be traced, not "
        "baked into the program shape")

    # -- 3: slots at equal HBM on a mixed short/long burst -------------
    # a ring engine must reserve 4 slots x full window; the paged pool
    # serves the SAME 4-slot workload token-identically from 1.6x fewer
    # cache bytes — short requests only hold the pages they touch, and
    # idle prefix-index pages are evicted under pressure
    mixed = []
    for i in range(8):
        n = 6 if i % 2 else 48  # short/long alternation
        mixed.append(list(map(int, rng.randint(3, 200, size=n))))
    want_mixed = ref_eng.generate(mixed, max_new_tokens=8,
                                  temperature=0.0)
    ring_equiv_pages = 4 * (CACHE // PS)
    pool_pages = int(ring_equiv_pages / 1.6)
    cap = paged(kv_pool_pages=pool_pages).warmup()
    got_mixed = cap.generate(mixed, max_new_tokens=8, temperature=0.0)
    assert got_mixed == want_mixed, (
        "mixed burst diverged on the constrained pool")
    stats = cap.paging_stats()
    slots_ratio = ring_equiv_pages / pool_pages
    assert slots_ratio >= 1.3 and stats["peak_pages_used"] <= pool_pages

    # -- 4: strict memplan refuses an over-budget pool pre-allocation --
    need = eng.hbm_required_bytes(slots=16)
    try:
        set_flags({"device_peaks": f"hbm_bytes={need - 1}",
                   "memory_budget_check": "strict"})
        try:
            GenerationEngine(model, slots=16, cache_len=CACHE,
                             prefill_buckets=BUCKETS,
                             kv_cache_layout="paged", kv_page_size=PS)
            raise AssertionError(
                "strict memplan admitted a page pool over the HBM "
                "budget")
        except MemoryBudgetError as e:
            assert "suggest_decode_slots" in str(e), e
        # the same budget admits a right-sized pool
        GenerationEngine(model, slots=2, cache_len=CACHE,
                         prefill_buckets=BUCKETS,
                         kv_cache_layout="paged", kv_page_size=PS)
    finally:
        set_flags({"memory_budget_check": "warn", "device_peaks": ""})

    print(f"paged-smoke OK: ring parity x{len(prompts)} at "
          f"{len(BUCKETS) + 1} compiles, {len(burst) - 1} shared-prefix "
          f"admits saved {flops_saved:.0%} prefill FLOPs (TTFT "
          f"{ttft_full * 1e3:.1f}ms -> {ttft_reused * 1e3:.1f}ms), "
          f"{slots_ratio:.2f}x slots at equal HBM (peak "
          f"{stats['peak_pages_used']}/{pool_pages} pages), strict "
          "memplan "
          "rejected the over-budget pool pre-allocation")
    return 0


if __name__ == "__main__":
    sys.exit(main())
