#!/usr/bin/env python
"""CI smoke for generative inference (`make gen-smoke`).

Stands up the full stack — tiny GPT causal LM, GenerationEngine
(bucketed prefill + compile-once ring-cache decode), ContinuousBatcher
slot scheduler, GenerationServer HTTP frontend — and asserts the
production contracts end to end:

- compile-bound generation: warmup costs exactly len(prefill ladder) + 1
  programs (``generation::compile`` counter), and a burst of
  mixed-length prompts afterwards costs ZERO more;
- parity: greedy tokens served over HTTP equal an independent engine's
  offline greedy decode of the same prompts (continuous batching and
  bucket padding are numerically inert);
- streaming: the ndjson stream delivers every token and a final summary
  line consistent with the non-streamed reply;
- /statz carries tokens/sec, slot occupancy, and per-token latency;
- graceful drain: ``stop(drain=True)`` finishes queued work, leaves no
  live slot, and kills the decode loop + listener.

Exit 0 on success; a failure is a real generation-serving regression.
"""
from __future__ import annotations

import json
import os
import sys
from urllib.error import HTTPError, URLError
from urllib.request import Request, urlopen

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

SLOTS = 2
CACHE_LEN = 32
BUCKETS = (4, 8)


def _post(url, payload, timeout=120):
    body = json.dumps(payload).encode()
    try:
        r = urlopen(Request(url + "/generate", data=body,
                            headers={"Content-Type": "application/json"}),
                    timeout=timeout)
        return r.status, json.loads(r.read())
    except HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def main():
    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.generation import COMPILE_COUNTER, GenerationEngine
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny_config
    from paddle_tpu.serving import GenerationServer

    paddle.seed(11)
    cfg = gpt_tiny_config()
    cfg.attention_window = CACHE_LEN
    model = GPTForCausalLM(cfg)

    rng = np.random.RandomState(0)
    prompts = [list(map(int, rng.randint(3, 200, size=n)))
               for n in (1, 3, 8, 5, 2, 7, 4, 6)]
    budgets = [int(b) for b in rng.randint(2, 10, size=len(prompts))]

    # independent reference engine: offline greedy, solo slots
    ref_eng = GenerationEngine(model, slots=1, cache_len=CACHE_LEN,
                               prefill_buckets=BUCKETS).warmup()
    refs = [ref_eng.generate([p], max_new_tokens=b, temperature=0.0)[0]
            for p, b in zip(prompts, budgets)]

    srv = GenerationServer(
        GenerationEngine(model, slots=SLOTS, cache_len=CACHE_LEN,
                         prefill_buckets=BUCKETS),
        port=0, queue_capacity=32)
    try:
        # -- readiness gating + exact warmup compile count -------------
        srv.start(warmup=False)
        try:
            urlopen(srv.url + "/healthz")
            raise AssertionError("/healthz must be 503 before warmup")
        except HTTPError as e:
            assert e.code == 503, e.code
        c0 = profiler.counters().get(COMPILE_COUNTER, 0)
        srv.warmup()
        warm = profiler.counters().get(COMPILE_COUNTER, 0) - c0
        assert warm == len(BUCKETS) + 1, (
            f"warmup cost {warm} compiles, expected prefill ladder "
            f"({len(BUCKETS)}) + 1 decode")
        hz = json.loads(urlopen(srv.url + "/healthz").read())
        assert hz["ready"] and hz["prefill_buckets"] == list(BUCKETS), hz

        # -- mixed-length burst: parity + zero extra compiles ----------
        for p, b, ref in zip(prompts, budgets, refs):
            status, out = _post(srv.url, {
                "prompt": p, "max_new_tokens": b, "temperature": 0.0})
            assert status == 200, (status, out)
            assert out["tokens"] == ref, (p, out["tokens"], ref)
        total = profiler.counters().get(COMPILE_COUNTER, 0) - c0
        assert total == len(BUCKETS) + 1, (
            f"burst grew compiles to {total}; the prefill ladder + "
            "single decode program must bound them")
        assert srv.engine.extra_compiles() == 0

        # -- streaming round trip --------------------------------------
        body = json.dumps({"prompt": prompts[0], "max_new_tokens":
                           budgets[0], "temperature": 0.0,
                           "stream": True}).encode()
        r = urlopen(Request(srv.url + "/generate", data=body), timeout=120)
        lines = [json.loads(l) for l in r.read().decode().splitlines()]
        toks = [l["token"] for l in lines if "token" in l]
        assert lines[-1].get("done") and lines[-1]["tokens"] == toks
        assert toks == refs[0], (toks, refs[0])

        # -- statz: tokens/sec, occupancy, per-token latency -----------
        sz = json.loads(urlopen(srv.url + "/statz").read())
        assert sz["generation"]["tokens_per_sec"] > 0, sz["generation"]
        assert sz["latency"]["token"]["p99_ms"] >= 0
        assert sz["compiles"]["unexpected"] == 0
        assert sz["requests"]["completed"] == len(prompts) + 1

        # -- graceful drain: no live slots, loop + listener down -------
        srv.stop(drain=True)
        assert srv.scheduler.live_slots == 0, "slots survived drain"
        assert srv.scheduler.alive == 0, "decode loop survived drain"
        try:
            urlopen(srv.url + "/healthz", timeout=2)
            raise AssertionError("listener still up after stop()")
        except (URLError, ConnectionError, OSError):
            pass
        print(f"gen-smoke OK: {len(BUCKETS)} prefill buckets + 1 decode "
              f"= {total} compiles, {sz['requests']['completed']} served, "
              f"{sz['generation']['tokens_generated']} tokens "
              f"(parity + streaming + drain verified)")
        return 0
    finally:
        srv.stop(drain=False)


if __name__ == "__main__":
    sys.exit(main())
