#!/usr/bin/env python
"""Summarize a chrome-trace JSON (paddle_tpu profiler / merged export).

The trace-viewer answers "what happened at t=1.23s"; this answers "where
did the time go" — the per-event aggregate the reference printed from
DisableProfiler, but over any exported trace file (host spans, the
merged host+device export, or a .trace.json.gz straight out of the jax
profiler run directory).

Usage:
    python tools/trace_summary.py trace.json
    python tools/trace_summary.py --sort calls --top 20 trace.json
    python tools/trace_summary.py --prefix executor:: trace.json
    python tools/trace_summary.py --trace-id 3f2a... merged.json

Reads complete-duration events (ph=X); sort keys mirror
profiler.print_summary (total/calls/max/ave descending, min ascending).
"""
from __future__ import annotations

import argparse
import gzip
import json
import sys


def load_trace(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        trace = json.load(f)
    if isinstance(trace, list):  # bare traceEvents array is also legal
        return trace
    return trace.get("traceEvents", [])


def filter_trace_id(events, trace_id):
    """Only events belonging to one distributed trace (the tracing
    spans embedded by export_merged_chrome_trace / ``/tracez`` carry
    their trace_id in ``args``). Prefix match, so the first 8+ hex
    chars from a /statz slowest row are enough."""
    return [ev for ev in events
            if str(ev.get("args", {}).get("trace_id", ""))
            .startswith(trace_id)]


def aggregate(events, prefix=None):
    agg = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "")
        if prefix and not name.startswith(prefix):
            continue
        dur_ms = float(ev.get("dur", 0)) / 1e3  # chrome trace us -> ms
        rec = agg.setdefault(
            name, {"calls": 0, "total": 0.0, "min": float("inf"),
                   "max": 0.0})
        rec["calls"] += 1
        rec["total"] += dur_ms
        rec["min"] = min(rec["min"], dur_ms)
        rec["max"] = max(rec["max"], dur_ms)
    for rec in agg.values():
        rec["ave"] = rec["total"] / rec["calls"]
    return agg


def render(agg, sort="total", top=0, file=sys.stdout):
    if not agg:
        print("No duration (ph=X) events in trace.", file=file)
        return
    ascending = sort == "min"
    items = sorted(agg.items(), key=lambda kv: kv[1][sort],
                   reverse=not ascending)
    if top:
        items = items[:top]
    grand = sum(r["total"] for r in agg.values()) or 1.0
    name_w = max(10, min(60, max(len(n) for n, _ in items)))
    header = (f"{'Event':<{name_w}}  {'Calls':>8}  {'Total(ms)':>12}  "
              f"{'Min(ms)':>10}  {'Max(ms)':>10}  {'Ave(ms)':>10}  "
              f"{'Ratio':>7}")
    print(header, file=file)
    print("-" * len(header), file=file)
    for name, r in items:
        print(f"{name[:name_w]:<{name_w}}  {r['calls']:>8}  "
              f"{r['total']:>12.4f}  {r['min']:>10.4f}  {r['max']:>10.4f}  "
              f"{r['ave']:>10.4f}  {r['total'] / grand:>7.4f}", file=file)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("trace", help="chrome-trace JSON (.json or .json.gz)")
    p.add_argument("--sort", default="total",
                   choices=["total", "calls", "min", "max", "ave"])
    p.add_argument("--top", type=int, default=0,
                   help="show only the first N rows (0: all)")
    p.add_argument("--prefix", default=None,
                   help="only events whose name starts with this "
                        "(e.g. executor:: / dataloader:: / collective::)")
    p.add_argument("--trace-id", default=None,
                   help="only spans of one distributed trace (hex id or "
                        "unique prefix, from /tracez or /statz slowest)")
    args = p.parse_args(argv)
    events = load_trace(args.trace)
    if args.trace_id:
        events = filter_trace_id(events, args.trace_id)
        if not events:
            print(f"no spans for trace_id {args.trace_id!r} in "
                  f"{args.trace}", file=sys.stderr)
            return 1
    agg = aggregate(events, prefix=args.prefix)
    render(agg, sort=args.sort, top=args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
