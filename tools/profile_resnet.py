"""Evidence-gathering for the ResNet-50 gap (VERDICT r3 item 1).

Experiments:
1. iters scaling: step-time at iters=5 vs 40 -> fixed dispatch overhead
2. jax.profiler device trace (if the axon backend supports it)
3. forward-only vs train-step split
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def build(batch=128, size=224, data_format="NCHW"):
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu import amp
    from paddle_tpu.framework import jit as fjit
    from paddle_tpu.models import resnet50

    paddle.seed(0)
    model = resnet50(num_classes=1000, data_format=data_format)
    optimizer = opt.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())

    def loss_fn(m, x, y):
        with amp.auto_cast():
            logits = m(x)
        return F.cross_entropy(logits.astype("float32"), y).mean()

    step = fjit.train_step(model, optimizer, loss_fn)
    rng = np.random.RandomState(0)
    shape = (batch, 3, size, size) if data_format == "NCHW" else (batch, size, size, 3)
    x = jax.device_put(rng.randn(*shape).astype("float32"))
    y = jax.device_put(rng.randint(0, 1000, (batch,)).astype("int64"))
    return model, step, x, y


def timeit(step, x, y, iters):
    float(np.asarray(step(x, y)["loss"]))
    float(np.asarray(step(x, y)["loss"]))
    t0 = time.perf_counter()
    for _ in range(iters):
        m = step(x, y)
    float(np.asarray(m["loss"]))
    return (time.perf_counter() - t0) / iters


def main():
    import jax

    batch = 128
    model, step, x, y = build(batch)
    t5 = timeit(step, x, y, 5)
    t40 = timeit(step, x, y, 40)
    # t(iters) = compute*iters + fetch_overhead => per-step at high iters
    print(json.dumps({"exp": "iters_scaling", "t_per_step_5": round(t5 * 1e3, 2),
                      "t_per_step_40": round(t40 * 1e3, 2),
                      "ips_40": round(batch / t40, 1)}), flush=True)

    # forward-only timing via the jitted eval step
    from paddle_tpu.framework import jit as fjit

    fwd_step = fjit.eval_step(model, lambda m, xx: m(xx).astype("float32").sum())
    float(np.asarray(fwd_step(x)))
    t0 = time.perf_counter()
    for _ in range(20):
        r = fwd_step(x)
    float(np.asarray(r))
    tf = (time.perf_counter() - t0) / 20
    print(json.dumps({"exp": "forward_only", "t_fwd_ms": round(tf * 1e3, 2),
                      "fwd_ips": round(batch / tf, 1)}), flush=True)

    # device trace attempt
    try:
        jax.profiler.start_trace("/tmp/resnet_trace")
        for _ in range(3):
            m = step(x, y)
        float(np.asarray(m["loss"]))
        jax.profiler.stop_trace()
        print(json.dumps({"exp": "trace", "ok": True, "dir": "/tmp/resnet_trace"}),
              flush=True)
    except Exception as e:
        print(json.dumps({"exp": "trace", "ok": False, "err": str(e)[:200]}),
              flush=True)


if __name__ == "__main__":
    main()
