#!/usr/bin/env bash
# CI driver (paddle/scripts/paddle_build.sh role: cmake_gen/build/run_test
# collapsed to what this runtime needs).
#
# Usage: tools/build_and_test.sh [fast|full|bench|check] [NSHARDS]
#   fast  - unit tests minus slow/subprocess ones
#   full  - entire suite (default); pass NSHARDS>1 to split the test
#           FILES across that many parallel pytest processes (xdist-safe
#           by construction: file granularity, no shared-scope state
#           crosses processes; compile-heavy files dominate wall time so
#           sharding gives near-linear speedup)
#   bench - bench.py smoke on the current backend
#   check - static gates: graphlint (framework-aware AST lint, waiver-
#           gated) + op coverage + API spec + graft entry self-test
#           + debugz smoke (debug server endpoints + flight-recorder dump)
#           + mfu smoke (cost-model capture + utilization endpoints)
#           + serving smoke (online batcher/replica/HTTP contracts)
#           + generation smoke (prefill ladder/compile-once decode,
#             KV-cache parity, streaming /generate, drain)
#           + router smoke (fleet tier: backend processes + router,
#             kill -9 mid-burst survival, eviction, clean drain)
#           + chaos smoke (elastic training: kill -9 mid-checkpoint-save,
#             resume resharded at a new world size, identical loss curve)
#           + tracez smoke (distributed tracing: one trace across
#             router->backend processes, tail retention of deadline+retry)
#           + kernel smoke (fused pallas kernels: numeric parity,
#             bounded compiles, prefetch-overlap input-wait drop)
#           + quant smoke (int8 end-to-end: kernel parity, int8 serving
#             programs, int8 KV cache, quantized all-reduce byte cut)
#           + spec smoke (speculative decoding: greedy token parity at
#             exact draft+verify compile counts, self-draft acceptance,
#             2-process prefill->decode fleet through the KV handoff)
#           + memplan smoke (static peak-HBM planner: plan-vs-XLA
#             accuracy envelope on BERT/ResNet/GPT smoke programs,
#             strict pre-compile admission naming the high-water op,
#             donation-safety golden, <1% steady-state dispatch cost)
#           + autotune smoke (kernel autotuner: pallas-vs-jnp parity on
#             layernorm + conv+bn+relu under default AND tuned
#             schedules, offline search with pre-compile pruning, the
#             JSON cache round-tripping into a fresh process with zero
#             re-search, corrupt cache degrading to defaults)
#           + ir-opt smoke (program-IR optimizer: fused-op counts > 0
#             on BERT/ResNet/GPT smoke programs with numeric goldens,
#             training-program byte-identity at level 1, and remat
#             converting a strict-mode rejection into an admit with
#             >= 20% planned-peak reduction)
#           + slo smoke (fleet SLO plane: labeled /metricz series, a
#             wedged backend paging via multi-window burn rate with a
#             slo_burn flight event, /fleetz quantiles equal to the
#             pooled-histogram golden, the scaler reading the burn)
#           + goodput smoke (training goodput ledger: >= 0.8 goodput
#             steady-state with 2% phase-conservation, kill -9 mid-save
#             resume continuing the lifetime ledger with recomputation
#             charged to lost_work)
#           + opprof smoke (per-op device-time attribution: >= 0.9
#             stamped-scope coverage + time-accuracy envelope on the
#             BERT/ResNet/GPT smokes, measured fused-conv win,
#             /profilez end to end, idle stamping < 1% of dispatch)
#           + paged smoke (paged KV: ring-vs-paged greedy parity at
#             bounded compiles, 90%-shared-prefix burst with the
#             prefill-FLOPs/TTFT win, >= 1.3x slots at equal HBM on a
#             constrained pool, strict memplan refusing an over-budget
#             pool before allocation)
#           + bench trend (two newest BENCH_r*.json, >20% headline
#             regressions warned)
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="${1:-full}"
NSHARDS="${2:-1}"

sharded_pytest() {
  # split test files round-robin over NSHARDS pytest processes
  local extra=("$@")
  mapfile -t files < <(ls tests/test_*.py | sort)
  local pids=() rc=0
  for ((s = 0; s < NSHARDS; s++)); do
    local shard=()
    for ((i = s; i < ${#files[@]}; i += NSHARDS)); do
      shard+=("${files[i]}")
    done
    # an empty shard must be a no-op (bare pytest would rediscover the
    # whole suite)
    [ "${#shard[@]}" -eq 0 ] && continue
    python -m pytest "${shard[@]}" -q -p no:cacheprovider "${extra[@]}" &
    pids+=($!)
  done
  for pid in "${pids[@]}"; do
    wait "$pid" || rc=1
  done
  return $rc
}

native_build() {
  # compile the native components into the cache (fails loudly here
  # rather than lazily at first use)
  python - <<'PY'
import jax; jax.config.update("jax_platforms", "cpu")
from paddle_tpu._native import ShmRing
from paddle_tpu._native.capi import build_capi
ShmRing._load()
print("shm_ring OK")
print("capi:", build_capi())
PY
}

case "$MODE" in
  fast)
    native_build
    python -m pytest tests/ -x -q -m "not slow"
    ;;
  full)
    native_build
    if [ "$NSHARDS" -gt 1 ]; then
      sharded_pytest
    else
      python -m pytest tests/ -q
    fi
    ;;
  bench)
    python bench.py
    ;;
  check)
    # graphlint gate first: pure AST (no jax), fails on any unwaived
    # finding or stale waiver (tools/graphlint_waivers.txt)
    python tools/graphlint.py --check
    python tools/check_op_coverage.py --min-pct 90
    python tools/print_signatures.py --check
    JAX_PLATFORMS=cpu python __graft_entry__.py
    # fault-diagnosis smoke: debug server up, endpoints valid, dump CLI works
    JAX_PLATFORMS=cpu python tools/debugz_smoke.py
    # utilization smoke: cost-model capture, MFU monitor line, /costz+/clusterz
    JAX_PLATFORMS=cpu python tools/utilization_smoke.py
    # serving smoke: warmed-bucket readiness, bounded compiles, 429, drain
    JAX_PLATFORMS=cpu python tools/serving_smoke.py
    # generation smoke: prefill ladder + single decode compile, KV-cache
    # parity over HTTP, streaming round trip, drain leaves no live slots
    JAX_PLATFORMS=cpu python tools/generation_smoke.py
    # router smoke: 2 backend processes + router, kill -9 one mid-burst
    # (zero client-visible failures), eviction counters, clean drain
    JAX_PLATFORMS=cpu python tools/router_smoke.py
    # chaos smoke: elastic training — kill -9 inside a checkpoint save,
    # resume at a DIFFERENT world size with ZeRO-1 state resharded, and
    # a loss curve identical to the uninterrupted run
    JAX_PLATFORMS=cpu python tools/chaos_smoke.py
    # tracez smoke: router + 2 backend processes — one trace_id across the
    # process hop with queue/dispatch stage spans, deadline-missed and
    # retried traces retained while the fast-path bulk is dropped
    JAX_PLATFORMS=cpu python tools/tracez_smoke.py
    # kernel smoke: fused optimizer-update + layernorm/residual numeric
    # parity (pallas interpret vs jnp, flag on/off through real call
    # sites), one-compile steady state, prefetch-overlap input-wait drop
    JAX_PLATFORMS=cpu python tools/kernel_smoke.py
    # quant smoke: int8 matmul kernel parity (pallas interpret == jnp,
    # bit-equal), PTQ -> save_int8_model served through a real
    # InferenceServer within the fp32 envelope at bounded compiles,
    # int8-KV decode == fp32 greedy tokens at >=1.8x slots/HBM, and the
    # quantized all-reduce's >=3.5x wire-byte cut from the ledger +
    # BERT-smoke loss convergence vs fp32
    JAX_PLATFORMS=cpu python tools/quant_smoke.py
    # spec smoke: speculative greedy decode token-identical to the plain
    # engine at exactly len(ladder)+2 compiles (draft + verify), self-
    # draft acceptance at the ceiling, and a real 1-prefill+1-decode
    # two-process fleet serving /generate through the KV-slab handoff
    # with zero unexpected compiles on either tier
    JAX_PLATFORMS=cpu python tools/spec_decode_smoke.py
    # memplan smoke: static liveness planner within the ±25% envelope of
    # XLA memory_analysis on BERT/ResNet/GPT smoke programs, strict mode
    # rejecting an over-budget program BEFORE compile with the
    # high-water op named, the donated-then-read golden rejected, and
    # the admission gate under 1% of the steady-state dispatch period
    JAX_PLATFORMS=cpu python tools/memplan_smoke.py
    # autotune smoke: kernel autotuner — layernorm + conv+bn+relu parity
    # under default and tuned schedules (fwd+bwd), offline search with
    # invalid candidates pruned before compile, the versioned JSON cache
    # round-tripping across a fresh process with zero re-search, and a
    # truncated cache degrading to defaults (one cache_reject, no crash)
    JAX_PLATFORMS=cpu python tools/autotune_smoke.py
    # ir-opt smoke: program-IR optimizer — conv+bn+relu / residual+ln /
    # int8-matmul fusions firing on BERT/ResNet/GPT inference smokes
    # with numeric goldens vs the unrewritten programs, a training
    # program (grad:: ops) passing through byte-identical at level 1,
    # and level-2 rematerialization turning a strict-budget rejection
    # into an admit at >= 20% planned-peak reduction
    JAX_PLATFORMS=cpu python tools/ir_opt_smoke.py
    # slo smoke: fleet SLO plane — labeled per-kind/tenant series on
    # /metricz (text + snapshot modes), one wedged backend driving its
    # fast+slow window burns past the alert threshold (slo_burn flight
    # event) while the healthy backend stays quiet, router /fleetz
    # p50/p99 exactly equal to the hand-merged pooled histogram, and
    # the autoscaler reading the confirmed burn as up-pressure
    JAX_PLATFORMS=cpu python tools/slo_smoke.py
    # goodput smoke: training goodput ledger — uninterrupted run at
    # goodput >= 0.8 with phase seconds summing to wall within 2%
    # (conservation), then a kill -9 inside a checkpoint save with the
    # resume continuing the lifetime ledger from the GOODPUT.json
    # sidecar (lifetime wall > post-restart wall) and the recomputed
    # steps charged to lost_work, not compute
    JAX_PLATFORMS=cpu python tools/goodput_smoke.py
    # opprof smoke: per-op device-time attribution — replay profiles of
    # the BERT/ResNet/GPT smokes with stamped-scope trace coverage
    # >= 0.9 and per-program time-accuracy inside the documented
    # envelope, top-op sanity (matmul/conv family leads by FLOPs), the
    # conv+bn+relu fusion win measured per op (not asserted from
    # theory), /profilez served end to end, and idle stamping under 1%
    # of the steady-state dispatch period
    JAX_PLATFORMS=cpu python tools/opprof_smoke.py
    # paged smoke: paged KV subsystem — ring-vs-paged greedy parity on
    # a mixed 8-prompt burst at exactly ladder+1 compiles, a
    # 90%-shared-prefix burst admitting through the radix index with
    # the prefill-FLOPs saving and a measured TTFT drop, the same
    # mixed short/long workload running token-identically on a pool
    # 1.6x smaller than the ring reservation (>= 1.3x slots at equal
    # HBM), and strict memplan refusing an over-budget pool at engine
    # construction, before any device allocation
    JAX_PLATFORMS=cpu python tools/paged_smoke.py
    # bench trend: two newest BENCH_r*.json compared, >20% headline
    # regressions warned (non-fatal: CPU-runner noise)
    python tools/bench_trend.py
    ;;
  *)
    echo "unknown mode: $MODE (fast|full|bench|check)" >&2
    exit 2
    ;;
esac
