#!/usr/bin/env bash
# CI driver (paddle/scripts/paddle_build.sh role: cmake_gen/build/run_test
# collapsed to what this runtime needs).
#
# Usage: tools/build_and_test.sh [fast|full|bench|check]
#   fast  - unit tests minus slow/subprocess ones
#   full  - entire suite (default)
#   bench - bench.py smoke on the current backend
#   check - static gates: op coverage + API spec + graft entry self-test
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="${1:-full}"

native_build() {
  # compile the native components into the cache (fails loudly here
  # rather than lazily at first use)
  python - <<'PY'
import jax; jax.config.update("jax_platforms", "cpu")
from paddle_tpu._native import ShmRing
from paddle_tpu._native.capi import build_capi
ShmRing._load()
print("shm_ring OK")
print("capi:", build_capi())
PY
}

case "$MODE" in
  fast)
    native_build
    python -m pytest tests/ -x -q -m "not slow"
    ;;
  full)
    native_build
    python -m pytest tests/ -q
    ;;
  bench)
    python bench.py
    ;;
  check)
    python tools/check_op_coverage.py --min-pct 90
    python tools/print_signatures.py --check
    JAX_PLATFORMS=cpu python __graft_entry__.py
    ;;
  *)
    echo "unknown mode: $MODE (fast|full|bench|check)" >&2
    exit 2
    ;;
esac
