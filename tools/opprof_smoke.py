#!/usr/bin/env python
"""Opprof smoke (ISSUE 19): per-op device-time attribution, certified.

Replay-profiles the BERT-, ResNet-, and GPT-shaped static smoke programs
(the ir_opt_smoke builders) and checks, end to end:

1. **Attribution coverage** — the stamped-scope trace attribution folds
   >= 0.9 of scored device/runtime time back onto ``op.type#<block>/
   <index>`` identities on every smoke program;
2. **Time-accuracy closure** — roofline-predicted program time vs
   replay-measured time lands inside the documented envelope
   (``monitor.opprof.TIME_ACCURACY_ENVELOPE``) on every smoke program;
3. **Top-op sanity** — the top-1 op by FLOPs is a matmul/conv-family op
   and a matmul/conv-family op sits in the top-3 by measured time;
4. **Fusion wins are measured, not asserted** — ``analysis.optimizer.
   measure_pass_deltas`` shows the fused conv+bn+relu measurably faster
   than the 3-op chain it replaced on the ResNet smoke;
5. **/profilez serves** — the debug endpoint returns the populated
   profile over HTTP (``?program=``/``?topk=`` views, 404 on unknown);
6. **Idle overhead** — the ``opprof_overhead`` bench row keeps the
   stamping cost under 1% of the dispatch period.

Run: ``make opprof-smoke`` (wired into ``tools/build_and_test.sh check``).
"""
from __future__ import annotations

import importlib.util
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

# matmul/conv-family registry op types: the compute-dense ops any real
# profile of these programs must rank at the top by FLOPs
_DENSE_FAMILY = ("matmul", "mul", "conv2d", "fused_conv_bn_relu",
                 "matmul_int8")


def _check(name, ok, detail=""):
    status = "ok" if ok else "FAIL"
    print(f"[opprof-smoke] {name}: {status} {detail}")
    if not ok:
        raise SystemExit(f"opprof smoke failed: {name} {detail}")


def _load_builders():
    """The ir_opt_smoke program builders (bench.py does the same)."""
    spec = importlib.util.spec_from_file_location(
        "ir_opt_smoke",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "ir_opt_smoke.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _profile_one(name, build):
    import paddle_tpu.static as static
    from paddle_tpu.monitor import opprof

    static.global_scope().clear()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        feeds, fetch = build()
    exe = static.Executor()
    exe.run_startup(startup)
    exe.run(main, feed=feeds, fetch_list=[fetch])
    prof = opprof.profile_program(main, feeds, name=name)
    print(f"[opprof-smoke] {name}: {prof['replayed_ops']}/{prof['n_ops']} "
          f"ops replayed, total {prof['total_us']:.1f}us, "
          f"coverage={prof['coverage']}, "
          f"time_accuracy={prof['time_accuracy']}")
    return prof


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.analysis import optimizer as iropt
    from paddle_tpu.monitor import opprof

    static.enable_static()
    builders = _load_builders()

    # 1+2+3) coverage, time-accuracy closure, top-op sanity on all three
    lo, hi = 1.0 / opprof.TIME_ACCURACY_ENVELOPE, opprof.TIME_ACCURACY_ENVELOPE
    for name, build in (("bert", builders.build_bert),
                        ("resnet", builders.build_resnet),
                        ("gpt", builders.build_gpt)):
        prof = _profile_one(name, build)
        _check(f"{name} attribution coverage >= 0.9",
               prof["coverage"] is not None and prof["coverage"] >= 0.9,
               f"(coverage {prof['coverage']})")
        _check(f"{name} time-accuracy within envelope",
               prof["time_accuracy"] is not None
               and lo <= prof["time_accuracy"] <= hi,
               f"({prof['time_accuracy']} in [{lo:.2f}, {hi:.1f}])")
        replayed = [r for r in prof["ops"] if r["replayed"]]
        by_flops = max(replayed, key=lambda r: r["flops"] or 0)
        by_time = sorted(replayed, key=lambda r: -r["time_us"])[:3]
        _check(f"{name} top-1 op by FLOPs is matmul/conv family",
               by_flops["op_type"] in _DENSE_FAMILY,
               f"({by_flops['scope']}, {by_flops['flops']:.0f} flops)")
        _check(f"{name} matmul/conv family in top-3 by time",
               any(r["op_type"] in _DENSE_FAMILY for r in by_time),
               f"({[r['scope'] for r in by_time]})")

    # 4) fused conv+bn+relu beats the 3-op chain it replaced, measured
    # per op through the same replay discipline (warmup=2, repeats=7:
    # best-of-N over enough repeats to shed scheduler noise on CI boxes)
    static.global_scope().clear()
    main_p, startup = static.Program(), static.Program()
    with static.program_guard(main_p, startup):
        feeds, fetch = builders.build_resnet()
    exe = static.Executor()
    exe.run_startup(startup)
    exe.run(main_p, feed=feeds, fetch_list=[fetch])
    fetch_name = fetch if isinstance(fetch, str) else fetch.name
    deltas = iropt.measure_pass_deltas(
        main_p, feeds, [fetch_name], level=1, name="resnet",
        warmup=2, repeats=7)
    _check("conv+bn+relu fusion rewrote the program", deltas["changed"],
           f"(passes {deltas['passes']})")
    chain_us = sum(
        deltas["deltas"].get(t, {}).get("before_us", 0.0)
        for t in ("conv2d", "batch_norm", "relu"))
    fused_us = deltas["deltas"].get(
        "fused_conv_bn_relu", {}).get("after_us", float("inf"))
    _check("fused conv+bn+relu measured faster than the 3-op chain",
           0.0 < fused_us < chain_us,
           f"(chain {chain_us:.1f}us -> fused {fused_us:.1f}us, "
           f"{chain_us / fused_us:.2f}x)")

    # 5) /profilez end to end over HTTP, populated from this very run
    import urllib.request

    from paddle_tpu import monitor

    srv = monitor.start_debug_server(port=0)
    try:
        body = json.load(urllib.request.urlopen(srv.url + "/profilez"))
        _check("/profilez serves the profile store",
               body["status"] == "ok"
               and {"bert", "resnet", "gpt"} <= set(body["programs"]),
               f"(programs {body['programs']})")
        body = json.load(urllib.request.urlopen(
            srv.url + "/profilez?program=resnet&topk=3"))
        _check("/profilez ?program=/?topk= views",
               body["program"] == "resnet" and len(body["ops"]) == 3
               and body["summary"]["coverage"] is not None,
               f"(top op {body['ops'][0]['scope']})")
    finally:
        monitor.stop_debug_server()

    # 6) idle overhead < 1% of the dispatch period (bench sub-row)
    import bench

    static.disable_static()
    row = bench.bench_opprof_overhead(iters_direct=5000)
    _check("idle stamping overhead < 1%", row["within_target"],
           f"({row['value']}% of {row['step_period_us']}us period; "
           f"per-stamp {row['per_stamp_us']}us, sampling "
           f"{row['sampling']['profile_ms']}ms unasserted)")

    print("[opprof-smoke] PASS")


if __name__ == "__main__":
    main()
