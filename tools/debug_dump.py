#!/usr/bin/env python
"""Pretty-print / filter a flight-recorder dump.

A dump (written on unhandled exception, SIGUSR1, watchdog trip, NaN
action=dump, or served live at /flightrecorder) is one JSON object; this
CLI turns it into the post-mortem views you actually read:

    python tools/debug_dump.py dump.json                 # header + events
    python tools/debug_dump.py dump.json --kind collective --group dp
    python tools/debug_dump.py dump.json --last 50       # tail only
    python tools/debug_dump.py dump.json --threads       # stack dump
    python tools/debug_dump.py dump.json --desync        # divergence report
    python tools/debug_dump.py dump.json --json          # filtered JSON out

Stdlib-only on purpose: it must run on the box that just crashed.
"""
from __future__ import annotations

import argparse
import collections
import json
import sys
import time


def _fmt_event(ev, t0):
    rel = ev.get("t", t0) - t0
    extras = " ".join(
        f"{k}={v}" for k, v in ev.items()
        if k not in ("i", "t", "kind"))
    return f"  [{ev.get('i', '?'):>6}] +{rel:9.3f}s {ev.get('kind'):<24} {extras}"


def _print_header(dump, out):
    when = time.strftime("%Y-%m-%d %H:%M:%S",
                         time.localtime(dump.get("time", 0)))
    print(f"flight-recorder dump — reason: {dump.get('reason')!r}",
          file=out)
    print(f"  at {when}  pid={dump.get('pid')}  rank={dump.get('rank')}"
          f"/{dump.get('world')}  uptime={dump.get('uptime_s')}s",
          file=out)
    evs = dump.get("events", [])
    print(f"  events: {len(evs)} in ring (recorded "
          f"{dump.get('events_recorded', len(evs))}, dropped "
          f"{dump.get('dropped', 0)})", file=out)
    by_kind = collections.Counter(e.get("kind") for e in evs)
    for kind, n in by_kind.most_common():
        print(f"    {kind:<24} {n}", file=out)
    tails = dump.get("collective_tails", {})
    if tails:
        print("  collective groups:", file=out)
        for g, t in sorted(tails.items()):
            last = t[-1] if t else None
            print(f"    {g:<12} {len(t)} calls in tail, last: {last}",
                  file=out)
    desync = dump.get("desync")
    if desync:
        divs = desync.get("divergences") or []
        missing = desync.get("missing_ranks") or []
        verdict = (f"{len(divs)} diverging group(s)" if divs
                   else "no divergence found")
        print(f"  desync exchange (tag {desync.get('tag')!r}): {verdict}"
              + (f"; ranks never answered: {missing}" if missing else ""),
              file=out)
        for d in divs:
            print(f"    !! {d.get('summary')}", file=out)


def _filter_events(dump, ns):
    evs = dump.get("events", [])
    if ns.kind:
        evs = [e for e in evs if e.get("kind") == ns.kind]
    if ns.group:
        evs = [e for e in evs if e.get("group") == ns.group]
    if ns.last:
        evs = evs[-ns.last:]
    return evs


def main(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("dump", help="flight-recorder dump JSON file")
    p.add_argument("--kind", help="only events of this kind "
                                  "(e.g. collective, executor_run_begin)")
    p.add_argument("--group", help="only collective events of this group")
    p.add_argument("--last", type=int, default=0,
                   help="only the last N (after filtering)")
    p.add_argument("--threads", action="store_true",
                   help="print the thread stacks instead of events")
    p.add_argument("--desync", action="store_true",
                   help="print the full desync report instead of events")
    p.add_argument("--json", action="store_true",
                   help="emit the filtered events as JSON")
    ns = p.parse_args(argv)

    try:
        with open(ns.dump) as f:
            dump = json.load(f)
    except (OSError, ValueError) as e:
        print(f"cannot read dump {ns.dump!r}: {e}", file=sys.stderr)
        return 2

    out = sys.stdout
    if ns.threads:
        for name, frames in sorted(dump.get("threads", {}).items()):
            print(f"--- thread {name} ---", file=out)
            for line in frames:
                print(line, file=out)
            print(file=out)
        return 0
    if ns.desync:
        json.dump(dump.get("desync"), out, indent=1)
        print(file=out)
        return 0

    evs = _filter_events(dump, ns)
    if ns.json:
        json.dump(evs, out, indent=1)
        print(file=out)
        return 0

    _print_header(dump, out)
    if ns.kind or ns.group or ns.last:
        label = " ".join(filter(None, (
            f"kind={ns.kind}" if ns.kind else "",
            f"group={ns.group}" if ns.group else "",
            f"last={ns.last}" if ns.last else "")))
        print(f"\nevents ({label}):", file=out)
    else:
        print("\nevents:", file=out)
    t0 = dump.get("events", [{}])[0].get("t", dump.get("time", 0)) \
        if dump.get("events") else dump.get("time", 0)
    for ev in evs:
        print(_fmt_event(ev, t0), file=out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
