#!/usr/bin/env python
"""CI smoke for speculative decoding + the disaggregated fleet
(`make spec-smoke`).

Four production contracts, end to end on the tiny GPT:

1. **Greedy token parity**: the speculative engine (1-layer truncated
   draft, k=4) emits EXACTLY the plain engine's greedy tokens on a
   mixed burst whose budgets wrap the ring — speculation is a latency
   optimization, never a numerics change.
2. **Self-draft sanity**: drafting with the target itself accepts
   (nearly) every proposal — acceptance rate must sit at the ceiling,
   and the truncated draft's acceptance must be > 0.
3. **Exact compile accounting**: warmup costs exactly
   len(prefill ladder) + 2 programs (draft + verify instead of the one
   decode program), and the burst afterwards compiles NOTHING.
4. **Two-process disaggregated fleet**: one ``--kind prefill`` backend
   + one ``--kind decode`` backend (real subprocesses over a
   ``save_gpt_model`` dir) behind a router serving ``/generate``
   through the prompt -> KV-slab -> decode handoff, token-identical to
   a single-process engine, with zero unexpected compiles on either
   tier.

Exit 0 on success; a failure is a real speculative/disaggregation
regression.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
from urllib.request import Request, urlopen

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

CACHE = 32
BUCKETS = (4, 8)
DRAFT_K = 4


def main():
    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.generation import COMPILE_COUNTER, GenerationEngine
    from paddle_tpu.models import (
        GPTForCausalLM,
        gpt_tiny_config,
        save_gpt_model,
        truncated_draft,
    )
    from paddle_tpu.serving import Router
    from paddle_tpu.serving.scaler import launch_process

    paddle.seed(11)
    cfg = gpt_tiny_config()
    cfg.attention_window = CACHE
    model = GPTForCausalLM(cfg)
    draft = truncated_draft(model, num_layers=1)

    rng = np.random.RandomState(0)
    prompts = [list(map(int, rng.randint(3, 200, size=n)))
               for n in (1, 3, 8, 5, 2, 7, 4, 6)]
    budgets = [int(b) for b in rng.randint(2, CACHE + 12,
                                           size=len(prompts))]

    # -- 1+3: greedy parity at exact compile counts --------------------
    plain = GenerationEngine(model, slots=2, cache_len=CACHE,
                             prefill_buckets=BUCKETS).warmup()
    refs = [plain.generate([p], max_new_tokens=b, temperature=0.0,
                           stop_at_eos=False)[0]
            for p, b in zip(prompts, budgets)]
    spec = GenerationEngine(model, slots=2, cache_len=CACHE,
                            prefill_buckets=BUCKETS,
                            draft_model=draft, draft_k=DRAFT_K)
    c0 = profiler.counters().get(COMPILE_COUNTER, 0)
    spec.warmup()
    warm = profiler.counters().get(COMPILE_COUNTER, 0) - c0
    assert warm == len(BUCKETS) + 2, (
        f"speculative warmup cost {warm} compiles, expected prefill "
        f"ladder ({len(BUCKETS)}) + draft + verify")
    for p, b, ref in zip(prompts, budgets, refs):
        got = spec.generate([p], max_new_tokens=b, temperature=0.0,
                            stop_at_eos=False)[0]
        assert got == ref, (p, got, ref)
    total = profiler.counters().get(COMPILE_COUNTER, 0) - c0
    assert total == len(BUCKETS) + 2, (
        f"burst grew compiles to {total}; draft+verify must stay "
        "compile-once")
    assert spec.extra_compiles() == 0
    stats = spec.spec_stats()
    assert stats["acceptance_rate"] is not None \
        and stats["acceptance_rate"] > 0, stats

    # -- 2: self-draft sanity ------------------------------------------
    selfd = GenerationEngine(model, slots=2, cache_len=CACHE,
                             prefill_buckets=BUCKETS,
                             draft_model=model, draft_k=DRAFT_K).warmup()
    selfd.generate(prompts[:3], max_new_tokens=10, temperature=0.0,
                   stop_at_eos=False)
    sstats = selfd.spec_stats()
    # not exactly 1.0: the draft chain's 1-token forwards and the
    # batched verify forward round differently in floating point, and
    # the ulp differences land in the two rings' cached K/V where they
    # compound — near-ties then argmax-flip. Typical 0.8-1.0; anything
    # near chance (1/vocab) would mean the draft/verify chains are
    # misaligned.
    assert sstats["acceptance_rate"] > 0.6, (
        "self-draft must accept (nearly) everything", sstats)

    # -- 4: two-process prefill+decode fleet through the handoff -------
    gpt_dir = tempfile.mkdtemp(prefix="ptpu_spec_smoke_")
    save_gpt_model(model, gpt_dir)
    common = ["--gpt-dir", gpt_dir, "--cache-len", str(CACHE),
              "--prefill-buckets", ",".join(map(str, BUCKETS))]
    procs = []
    try:
        pre = launch_process(
            "paddle_tpu.serving.backend",
            ["--kind", "prefill", *common, "--slots", "1"],
            startup_timeout_s=180)
        procs.append(pre)
        dec = launch_process(
            "paddle_tpu.serving.backend",
            ["--kind", "decode", *common, "--slots", "2"],
            startup_timeout_s=180)
        procs.append(dec)
        router = Router(backends=[pre.url, dec.url]).start()
        try:
            hz = {u: json.loads(urlopen(u + "/healthz").read())
                  for u in (pre.url, dec.url)}
            assert hz[pre.url]["kind"] == "prefill", hz
            assert hz[dec.url]["kind"] == "decode", hz
            prompt, budget = prompts[2], budgets[2]
            want = plain.generate([prompt], max_new_tokens=budget,
                                  temperature=0.0, stop_at_eos=False)[0]
            body = json.dumps({"prompt": prompt,
                               "max_new_tokens": budget,
                               "temperature": 0.0}).encode()
            r = urlopen(Request(
                router.url + "/generate", data=body,
                headers={"Content-Type": "application/json"}),
                timeout=180)
            out = json.loads(r.read())
            assert out["tokens"] == want, (out["tokens"], want)
            assert out["prompt_tokens"] == len(prompt)
            for u in (pre.url, dec.url):
                lz = json.loads(urlopen(u + "/loadz").read())
                assert lz["compiles"]["unexpected"] == 0, (u, lz)
        finally:
            router.stop(drain=False)
    finally:
        for h in procs:
            try:
                h.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        for h in procs:
            try:
                h.proc.wait(20)
            except Exception:  # noqa: BLE001
                h.proc.kill()

    print(f"spec-smoke OK: greedy parity x{len(prompts)} at "
          f"{len(BUCKETS) + 2} compiles (draft+verify), acceptance "
          f"{stats['acceptance_rate']} (self-draft "
          f"{sstats['acceptance_rate']}), 2-process prefill->decode "
          "handoff token-identical with 0 unexpected compiles")
    return 0


if __name__ == "__main__":
    sys.exit(main())
