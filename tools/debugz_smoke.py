#!/usr/bin/env python
"""CI smoke for the fault-diagnosis stack (`make debugz-smoke`).

Starts a run with the debug server on, drives a few executor steps,
curls ``/healthz`` + ``/flightrecorder`` (plus /metrics, /threadz,
/flagz), validates the JSON, then round-trips a dump file through
``tools/debug_dump.py``. Exit 0 on success; nothing here depends on
timing — a failure is a real regression in the diagnosis path.
"""
from __future__ import annotations

import io
import json
import os
import sys
import tempfile
from contextlib import redirect_stdout
from urllib.request import urlopen

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    import paddle_tpu.static as static
    from paddle_tpu import ops
    from paddle_tpu.monitor import debug_server, flight_recorder as fr

    import debug_dump  # tools/ sibling (sys.path[0] is tools/ when run)

    static.enable_static()
    static.reset_default_programs()
    static.global_scope().clear()
    srv = debug_server.DebugServer(port=0).start()
    try:
        # -- a tiny live run for the endpoint to look at -------------------
        x = static.data("x", [8, 4], "float32")
        w = static.nn.create_parameter([4, 1], "float32")
        loss = ops.mean(ops.square(ops.matmul(x, w)))
        opt = static.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
        exe = static.Executor()
        exe.run_startup()
        X = np.random.RandomState(0).randn(8, 4).astype("float32")
        for _ in range(3):
            exe.run(feed={"x": X}, fetch_list=[loss])

        # -- curl the endpoints, validate the JSON -------------------------
        health = json.loads(urlopen(srv.url + "/healthz").read())
        assert health["ok"] is True, health
        assert health["pid"] == os.getpid()
        assert health["flight_recorder"]["events_recorded"] > 0, health
        assert health["last_progress"] == "executor_run", health

        snap = json.loads(urlopen(srv.url + "/flightrecorder").read())
        kinds = [e["kind"] for e in snap["events"]]
        assert kinds.count("executor_run_begin") == 3, kinds
        assert kinds.count("executor_run_end") == 3, kinds
        begins = [e for e in snap["events"]
                  if e["kind"] == "executor_run_begin"]
        assert begins[0]["jit_cache"] == "miss"
        assert begins[-1]["jit_cache"] == "hit"

        prom = urlopen(srv.url + "/metrics").read().decode()
        assert "# TYPE" in prom and "executor__jit_cache_hit" in prom

        threadz = urlopen(srv.url + "/threadz").read().decode()
        assert "MainThread" in threadz

        flagz = json.loads(urlopen(srv.url + "/flagz").read())
        assert "watchdog_timeout_s" in flagz and "debug_port" in flagz

        # -- dump file → debug_dump CLI round trip -------------------------
        out_dir = tempfile.mkdtemp(prefix="ptpu_debugz_smoke_")
        dump_path = fr.dump_now(reason="debugz_smoke",
                                path=os.path.join(out_dir, "dump.json"))
        with open(dump_path) as f:
            dump = json.load(f)
        assert dump["reason"] == "debugz_smoke"
        assert dump["threads"], "dump must carry thread stacks"

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = debug_dump.main([dump_path])
        assert rc == 0 and "executor_run_begin" in buf.getvalue()
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = debug_dump.main([dump_path, "--kind", "executor_run_end",
                                  "--json"])
        assert rc == 0 and all(
            e["kind"] == "executor_run_end" for e in json.loads(buf.getvalue()))

        print(f"debugz-smoke OK: {len(snap['events'])} recorder events, "
              f"{len(prom.splitlines())} prometheus lines, "
              f"debug server on {srv.url} -> {dump_path}")
        return 0
    finally:
        srv.stop()
        static.disable_static()
        static.reset_default_programs()
        static.global_scope().clear()


if __name__ == "__main__":
    sys.exit(main())
