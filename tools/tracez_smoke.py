#!/usr/bin/env python
"""CI smoke for distributed request tracing (`make tracez-smoke`).

Boots the real fleet shape — two backend processes behind an in-process
Router — and asserts the tracing contracts an on-call operator depends
on:

- **cross-process continuity**: one request's trace is retained on BOTH
  sides of the router hop with a consistent identity — the router store
  holds ``serving::router`` -> ``serving::attempt``; the chosen
  backend's ``/tracez`` holds the SAME trace_id with its
  ``serving::predict`` root parented under the router's attempt span id,
  plus queue-wait / assemble / dispatch stage spans, the dispatch span
  carrying the plan/jit cache disposition and cost-model FLOPs;
- **tail sampling keeps the interesting tails**: a deadline-missed
  request's trace is flagged and retained backend-side; a request that
  survives a backend SIGKILL via retry-on-next-backend is retained
  router-side with one trace_id spanning two attempt spans (the first
  errored); the fast-path bulk is demonstrably dropped;
- **operator surface**: backend ``/statz`` exposes the ``slowest`` table
  (trace_id + stage breakdown) and ``tools/trace_summary.py
  --trace-id`` filters a chrome-trace export down to one trace.

Exit 0 on success; a failure is a real tracing regression.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import time
from urllib.error import HTTPError, URLError
from urllib.request import Request, urlopen

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

BUCKETS = (1, 2, 4)
IN_DIM = 16


def _build_model_dir():
    import paddle_tpu.static as static

    static.enable_static()
    static.reset_default_programs()
    static.global_scope().clear()
    try:
        x = static.data("x", [None, IN_DIM], "float32")
        h = static.nn.fc(x, 64, name="tsm_fc1")
        y = static.nn.fc(h, 8, name="tsm_fc2")
        exe = static.Executor()
        exe.run_startup()
        d = tempfile.mkdtemp(prefix="ptpu_tracez_smoke_")
        static.save_inference_model(d, ["x"], [y], exe)
    finally:
        static.disable_static()
        static.reset_default_programs()
    return d


def _post(url, rows, deadline_ms=None, timeout=30):
    a = np.random.RandomState(rows).randn(rows, IN_DIM).astype("float32")
    payload = {"inputs": a.tolist()}
    if deadline_ms is not None:
        payload["deadline_ms"] = deadline_ms
    body = json.dumps(payload).encode()
    try:
        r = urlopen(Request(url + "/predict", data=body,
                            headers={"Content-Type": "application/json"}),
                    timeout=timeout)
        return r.status
    except HTTPError as e:
        e.read()
        return e.code


def _get(url, timeout=10):
    try:
        return json.loads(urlopen(url, timeout=timeout).read())
    except HTTPError as e:
        return json.loads(e.read() or b"{}")


def _backend_trace(handles, tid):
    """Fetch one retained trace from whichever backend holds it."""
    for h in handles:
        try:
            tr = _get(h.url + f"/tracez?id={tid}")
        except (URLError, ConnectionError, OSError):
            continue
        if tr.get("trace_id") == tid:
            return h, tr
    return None, None


def main():
    from paddle_tpu.monitor import tracing
    from paddle_tpu.serving import Router, SubprocessLauncher

    model_dir = _build_model_dir()
    # a generous batch window so a tiny deadline reliably expires in the
    # queue (the deadline-retention leg below)
    launcher = SubprocessLauncher(
        model_dir, buckets=BUCKETS, batch_timeout_ms=20.0,
        queue_capacity=64)
    print("booting 2 backend processes ...", flush=True)
    handles = [launcher.launch(), launcher.launch()]
    router = Router(backends=[h.url for h in handles],
                    probe_interval_s=5.0).start()
    try:
        assert router.healthy_count == 2, router.healthz()

        # -- cross-process continuity ----------------------------------
        # the FIRST finished traces of a sampling window are always
        # retained (they seed the slowest-K race), so this request's
        # trace is deterministically kept on both sides of the hop
        assert _post(router.url, rows=2) == 200
        tz = _get(router.url + "/tracez")
        rows = [t for t in tz["retained"]
                if t["root"] == "serving::router"]
        assert rows, tz["retained"]
        tid = rows[-1]["trace_id"]
        rt = _get(router.url + f"/tracez?id={tid}")
        attempts = [s for s in rt["spans"]
                    if s["name"] == "serving::attempt"]
        root = [s for s in rt["spans"]
                if s["name"] == "serving::router"][0]
        assert attempts and attempts[0]["parent_id"] == root["span_id"]
        assert attempts[0]["attrs"]["status"] == 200, attempts
        h, bt = _backend_trace(handles, tid)
        assert bt is not None, (
            f"trace {tid} not retained on any backend — the traceparent "
            "hop or backend-side retention is broken")
        names = {s["name"] for s in bt["spans"]}
        assert {"serving::predict", "serving::queue_wait",
                "serving::assemble", "serving::dispatch"} <= names, names
        pred = [s for s in bt["spans"]
                if s["name"] == "serving::predict"][0]
        assert pred["trace_id"] == tid
        assert pred["parent_id"] in {a["span_id"] for a in attempts}, (
            "backend root must hang under the router's attempt span",
            pred, attempts)
        disp = [s for s in bt["spans"]
                if s["name"] == "serving::dispatch"][0]
        assert disp["attrs"].get("plan_cache") in ("hit", "miss"), disp
        assert disp["attrs"].get("jit_cache") in ("hit", "miss"), disp
        assert disp["attrs"].get("flops", 0) > 0, disp
        assert any(link["trace_id"] == tid
                   for link in disp.get("links", [])), disp
        print(f"continuity OK: trace {tid[:8]}… spans both processes "
              f"(router root -> attempt -> {h.url} predict/queue/"
              "dispatch), dispatch carries "
              f"plan_cache={disp['attrs']['plan_cache']} "
              f"flops={disp['attrs']['flops']}", flush=True)

        # -- operator surface: /statz slowest + trace_summary ----------
        sz = _get(h.url + "/statz")
        assert sz["slowest"] and sz["slowest"][0]["trace_id"], sz.get(
            "slowest")
        assert any(r["trace_id"] == tid for r in sz["slowest"]), (
            sz["slowest"])
        chrome = _get(h.url + f"/tracez?id={tid}&format=chrome")
        assert chrome["traceEvents"], chrome
        with tempfile.NamedTemporaryFile(
                "w", suffix=".json", delete=False) as f:
            json.dump(chrome, f)
            trace_path = f.name
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import trace_summary

        events = trace_summary.load_trace(trace_path)
        mine = trace_summary.filter_trace_id(events, tid[:12])
        other = trace_summary.filter_trace_id(events, "f" * 32)
        assert mine and not other, (len(mine), len(other))
        assert trace_summary.main(
            ["--trace-id", tid[:12], trace_path]) == 0
        print(f"operator surface OK: /statz slowest names the trace, "
              f"trace_summary --trace-id keeps {len(mine)} spans",
              flush=True)

        # -- tail sampling: deadline-missed trace retained -------------
        # a deadline can only expire while QUEUED behind other work, so
        # wedge both backends with a burst and race a tiny deadline
        # against it (retried until the race is won — each attempt is
        # legitimate traffic)
        import threading

        stop = threading.Event()

        def storm(url):
            while not stop.is_set():
                _post(url, rows=4)

        # wedge the backends DIRECTLY (the in-process router would GIL-
        # throttle a storm routed through it, leaving the backend queues
        # shallow); the probe still goes through the router — whichever
        # backend p2c picks is wedged
        storm_threads = [threading.Thread(target=storm, args=(h.url,))
                         for h in handles for _ in range(8)]
        for t in storm_threads:
            t.start()
        try:
            time.sleep(0.1)  # let the queues build real depth
            status = None
            for _ in range(50):
                status = _post(router.url, rows=1, deadline_ms=2)
                if status == 504:
                    break
        finally:
            stop.set()
            for t in storm_threads:
                t.join()
        assert status == 504, status
        deadline_kept = None
        for hh in handles:
            for row in _get(hh.url + "/tracez")["retained"]:
                if "deadline" in row["kept"]:
                    deadline_kept = (hh, row)
        assert deadline_kept is not None, (
            "deadline-expired trace must be flagged and retained")
        dtr = _get(deadline_kept[0].url
                   + f"/tracez?id={deadline_kept[1]['trace_id']}")
        qw = [s for s in dtr["spans"]
              if s["name"] == "serving::queue_wait"][0]
        assert "deadline" in qw.get("error", ""), qw
        print("tail sampling OK: deadline miss retained with an errored "
              "queue-wait span", flush=True)

        # -- tail sampling: retried trace retained ---------------------
        # the storm left the router's probed queue depths stale-high;
        # refresh them, then kill the backend the router will PREFER at
        # the next dispatch (same (score, url) key as its p2c pick) so
        # the very next post provably hits the dead backend and retries
        # — killing an arbitrary backend raced the prober's eviction
        router.probe_once()
        preferred = min(router.backend_states(),
                        key=lambda b: (b.score(), b.url))
        victim = next(h for h in handles if h.url == preferred.url)
        survivor = next(h for h in handles if h is not victim)
        os.kill(victim.proc.pid, signal.SIGKILL)
        victim.proc.wait(10)
        # retry-on-next-backend must make the kill invisible; the trace
        # records both attempts under ONE id and is flagged "retry"
        deadline = time.monotonic() + 30
        retried = None
        while retried is None and time.monotonic() < deadline:
            assert _post(router.url, rows=1) == 200
            for row in _get(router.url + "/tracez")["retained"]:
                if "retry" in row["kept"]:
                    retried = row
        assert retried is not None, "no retried trace retained"
        rtr = _get(router.url + f"/tracez?id={retried['trace_id']}")
        atts = [s for s in rtr["spans"]
                if s["name"] == "serving::attempt"]
        assert len(atts) >= 2, atts
        assert len({s["trace_id"] for s in atts}) == 1
        assert len({s["span_id"] for s in atts}) == len(atts)
        failed = [s for s in atts if s.get("error")]
        ok = [s for s in atts if s["attrs"].get("status") == 200]
        assert failed and ok, atts
        assert failed[0]["attrs"]["backend"] == victim.url, failed
        assert ok[0]["attrs"]["backend"] == survivor.url, ok
        print(f"tail sampling OK: retried trace kept — one trace_id, "
              f"{len(atts)} distinct attempt spans "
              f"(failed={failed[0]['attrs']['backend']})", flush=True)

        # -- the boring bulk is dropped --------------------------------
        for i in range(40):
            assert _post(router.url, rows=(i % 3) + 1) == 200
        stats = tracing.store().stats()
        assert stats["dropped"] > 0, (
            "fast-path bulk must be dropped by the tail sampler", stats)
        print(f"bulk dropped OK: router store finished="
              f"{stats['finished']} retained={stats['retained']} "
              f"dropped={stats['dropped']}", flush=True)

        # -- clean teardown --------------------------------------------
        launcher.terminate(survivor, drain=True)
        assert survivor.proc.returncode == 0
        router.stop(drain=True)
        print("tracez-smoke OK: cross-process trace continuity, tail "
              "retention of deadline+retry, bulk dropped")
        return 0
    finally:
        router.stop(drain=False)
        for h in handles:
            try:
                launcher.terminate(h, drain=False, timeout_s=5)
            except Exception:
                pass


if __name__ == "__main__":
    sys.exit(main())
