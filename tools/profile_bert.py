"""BERT-base train-step device profile + HLO cost stats (headline-metric
evidence, companion to tools/hlo_resnet.py)."""
from __future__ import annotations

import collections
import gzip
import json
import glob
import os
import re

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import amp
    from paddle_tpu.framework import jit as fjit
    from paddle_tpu.models import (
        BertConfig, BertForPretraining, BertPretrainingCriterion,
    )

    cfg = BertConfig(use_flash_attention=True)
    batch, seq, n_pred = 128, 128, 20
    paddle.seed(0)
    model = BertForPretraining(cfg)
    crit = BertPretrainingCriterion(cfg.vocab_size)
    optimizer = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())

    def loss_fn(m, ids, tt, pos, mlm, nsp):
        with amp.auto_cast():
            pred, rel = m(ids, tt, masked_positions=pos)
        return crit(pred.astype("float32"), rel.astype("float32"), mlm, nsp)

    step = fjit.train_step(model, optimizer, loss_fn)
    rng = np.random.RandomState(0)
    ids = jax.device_put(rng.randint(1, cfg.vocab_size, (batch, seq)).astype("int64"))
    tt = jax.device_put(rng.randint(0, 2, (batch, seq)).astype("int64"))
    pos = jax.device_put(np.stack(
        [rng.choice(seq, n_pred, replace=False) + i * seq for i in range(batch)]
    ).ravel().astype("int64"))
    mlm = jax.device_put(rng.randint(0, cfg.vocab_size, (batch * n_pred,)).astype("int64"))
    nsp = jax.device_put(rng.randint(0, 2, (batch, 1)).astype("int64"))

    # HLO cost stats
    lr = jax.numpy.asarray(1e-4, jax.numpy.float32)
    key = jax.random.PRNGKey(0)
    batch_args = (ids, tt, pos, mlm, nsp)
    compiled = jax.jit(step.pure).lower(step.state, batch_args, lr, key).compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    txt = compiled.as_text()
    convs = collections.Counter(
        m.group(1).split("[")[0]
        for m in re.finditer(r"= (\S+) (?:convolution|dot)\(", txt)
    )
    print(json.dumps({
        "flops_T": round(ca.get("flops", 0) / 1e12, 2),
        "bytes_GB": round(ca.get("bytes accessed", 0) / 1e9, 2),
        "matmul_dtypes": dict(convs),
    }), flush=True)

    # device trace
    float(np.asarray(step(*batch_args)["loss"]))
    float(np.asarray(step(*batch_args)["loss"]))
    jax.profiler.start_trace("/tmp/bert_trace")
    for _ in range(3):
        m = step(*batch_args)
    float(np.asarray(m["loss"]))
    jax.profiler.stop_trace()

    run = sorted(os.listdir("/tmp/bert_trace/plugins/profile"))[-1]
    path = sorted(glob.glob(
        f"/tmp/bert_trace/plugins/profile/{run}/*.trace.json.gz"))[-1]
    with gzip.open(path) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    pids = {e["pid"]: e["args"]["name"] for e in evs
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    agg = collections.Counter()
    for e in evs:
        if e.get("ph") == "X" and "TPU" in pids.get(e["pid"], ""):
            n = e["name"]
            if n.startswith("jit_pure") or n.isdigit():
                continue
            agg[n] += e.get("dur", 0)
    total = sum(agg.values())
    print(json.dumps({"device_ms_per_step": round(total / 3e3, 2)}), flush=True)
    for name, d in agg.most_common(20):
        print(f"{d/3e3:8.3f} ms/step  {name[:80]}", flush=True)


if __name__ == "__main__":
    main()
