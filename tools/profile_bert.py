"""BERT-base train-step device profile + HLO cost stats (headline-metric
evidence, companion to tools/hlo_resnet.py)."""
from __future__ import annotations

import collections
import gzip
import json
import glob
import os
import re

import numpy as np


def main():
    import jax

    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.bert_step_common import build_bert_step

    step, batch_args = build_bert_step(device_put=True)

    # HLO cost stats (shared normalization/guard: monitor.cost_model)
    from paddle_tpu.monitor import cost_model

    lr = jax.numpy.asarray(1e-4, jax.numpy.float32)
    key = jax.random.PRNGKey(0)
    compiled = jax.jit(step.pure).lower(step.state, batch_args, lr, key).compile()
    ca = cost_model.analyze_cost(compiled) or {}
    txt = compiled.as_text()
    convs = collections.Counter(
        m.group(1).split("[")[0]
        for m in re.finditer(r"= (\S+) (?:convolution|dot)\(", txt)
    )
    print(json.dumps({
        "flops_T": round(ca.get("flops", 0) / 1e12, 2),
        "bytes_GB": round(ca.get("bytes accessed", 0) / 1e9, 2),
        "matmul_dtypes": dict(convs),
    }), flush=True)

    # device trace
    float(np.asarray(step(*batch_args)["loss"]))
    float(np.asarray(step(*batch_args)["loss"]))
    jax.profiler.start_trace("/tmp/bert_trace")
    for _ in range(3):
        m = step(*batch_args)
    float(np.asarray(m["loss"]))
    jax.profiler.stop_trace()

    run = sorted(os.listdir("/tmp/bert_trace/plugins/profile"))[-1]
    path = sorted(glob.glob(
        f"/tmp/bert_trace/plugins/profile/{run}/*.trace.json.gz"))[-1]
    with gzip.open(path) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    pids = {e["pid"]: e["args"]["name"] for e in evs
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    agg = collections.Counter()
    for e in evs:
        if e.get("ph") == "X" and "TPU" in pids.get(e["pid"], ""):
            n = e["name"]
            if n.startswith("jit_pure") or n.isdigit():
                continue
            agg[n] += e.get("dur", 0)
    total = sum(agg.values())
    print(json.dumps({"device_ms_per_step": round(total / 3e3, 2)}), flush=True)
    for name, d in agg.most_common(20):
        print(f"{d/3e3:8.3f} ms/step  {name[:80]}", flush=True)


if __name__ == "__main__":
    main()
