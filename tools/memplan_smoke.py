#!/usr/bin/env python
"""Memplan smoke (ISSUE 14): the static peak-HBM planner, certified.

Plans BERT-, ResNet-, and GPT-shaped static smoke programs and checks,
end to end through ``Executor.run``:

1. **Accuracy envelope** — ``plan_accuracy`` (predicted peak vs XLA's
   own ``memory_analysis``: argument + output + temp − alias) lands
   inside the documented envelope (``analysis.memory.ACCURACY_ENVELOPE``
   = ±25%) on every smoke program;
2. **Strict admission** — ``FLAGS_memory_budget_check=strict`` rejects a
   deliberately over-budget program BEFORE any compile, naming the
   high-water op and top tensors, and rejects the donated-then-read
   donation-safety golden naming the offending var;
3. **Steady-state overhead** — the ``executor_dispatch.memplan`` bench
   sub-row keeps the admission gate under 1% of the dispatch period
   (cached verdicts per program version, the PR-13 verifier-cache
   discipline).

Run: ``make memplan-smoke`` (wired into ``tools/build_and_test.sh check``).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _check(name, ok, detail=""):
    status = "ok" if ok else "FAIL"
    print(f"[memplan-smoke] {name}: {status} {detail}")
    if not ok:
        raise SystemExit(f"memplan smoke failed: {name} {detail}")


def _run_one(name, build):
    """Build one smoke program, run a step, return its CostRecord."""
    import paddle_tpu.static as static
    from paddle_tpu.monitor import cost_model

    # each program names its params param_N from 0: the shared global
    # scope must not leak a previous program's arrays into this one
    static.global_scope().clear()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        feeds, fetch = build()
        exe = static.Executor()
        exe.run_startup()
        out = exe.run(feed=feeds, fetch_list=[fetch])
        loss = float(np.asarray(out[0]))
    rec = cost_model.latest_record("executor")
    assert rec is not None, f"{name}: no cost record captured"
    plan = main.plan_memory(
        feed_names=sorted(feeds), fetch_list=[fetch],
        feed_shapes={k: np.shape(v) for k, v in feeds.items()})
    print(f"[memplan-smoke] {name}: loss={loss:.4f} "
          f"predicted={plan.peak_bytes} "
          f"(high-water op #{plan.peak_op_index} <{plan.peak_op_type}>) "
          f"actual={rec.argument_bytes + rec.output_bytes + rec.temp_bytes - rec.alias_bytes} "
          f"plan_accuracy={rec.plan_accuracy}")
    return rec, plan


def build_bert():
    """BERT-shaped: embedding + 2 fc+layernorm blocks + MLM-ish head."""
    import paddle_tpu.static as static
    from paddle_tpu import ops

    B, S, E, V = 16, 32, 64, 512
    ids = static.data("ids", [B, S], "int64")
    label = static.data("label", [B * S, 1], "int64")
    table = static.nn.create_parameter([V, E], "float32")
    h = ops.embedding(ids, table)
    h = ops.reshape(h, [B * S, E])
    for i in range(2):
        h = static.nn.layer_norm(
            static.nn.fc(h, E, activation="relu", name=f"enc{i}"))
    logits = static.nn.fc(h, V, name="mlm")
    loss = ops.mean(ops.softmax_with_cross_entropy(logits, label))
    static.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feeds = {"ids": rng.randint(0, V, (B, S)).astype("int64"),
             "label": rng.randint(0, V, (B * S, 1)).astype("int64")}
    return feeds, loss


def build_resnet():
    """ResNet-shaped: conv+bn+relu stem, pool, fc classifier."""
    import paddle_tpu.static as static
    from paddle_tpu import ops

    B = 8
    img = static.data("img", [B, 3, 16, 16], "float32")
    label = static.data("label", [B, 1], "int64")
    h = static.nn.conv2d(img, num_filters=8, filter_size=3, padding=1,
                         name="c1")
    h = ops.relu(static.nn.batch_norm(h))
    h = static.nn.conv2d(h, num_filters=16, filter_size=3, padding=1,
                         name="c2")
    h = ops.relu(static.nn.batch_norm(h))
    h = ops.max_pool2d(h, 2, stride=2)
    logits = static.nn.fc(h, 10, name="head")
    loss = ops.mean(ops.softmax_with_cross_entropy(logits, label))
    static.optimizer.Momentum(learning_rate=1e-2).minimize(loss)
    rng = np.random.RandomState(1)
    feeds = {"img": rng.randn(B, 3, 16, 16).astype("float32"),
             "label": rng.randint(0, 10, (B, 1)).astype("int64")}
    return feeds, loss


def build_gpt():
    """GPT-shaped: tied-embedding LM head over an fc decoder stack."""
    import paddle_tpu.static as static
    from paddle_tpu import ops

    B, S, E, V = 8, 32, 64, 512
    ids = static.data("ids", [B, S], "int64")
    label = static.data("label", [B * S, 1], "int64")
    table = static.nn.create_parameter([V, E], "float32")
    h = ops.reshape(ops.embedding(ids, table), [B * S, E])
    for i in range(3):
        h = static.nn.layer_norm(
            static.nn.fc(h, E, activation="relu", name=f"blk{i}"))
    logits = ops.matmul(h, ops.transpose(table, [1, 0]))  # tied head
    loss = ops.mean(ops.softmax_with_cross_entropy(logits, label))
    static.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    rng = np.random.RandomState(2)
    feeds = {"ids": rng.randint(0, V, (B, S)).astype("int64"),
             "label": rng.randint(0, V, (B * S, 1)).astype("int64")}
    return feeds, loss


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.analysis import DonationError, MemoryBudgetError
    from paddle_tpu.analysis.memory import ACCURACY_ENVELOPE
    from paddle_tpu.flags import set_flags

    static.enable_static()

    # 1) plan accuracy within the documented envelope on all three
    for name, build in (("bert", build_bert), ("resnet", build_resnet),
                        ("gpt", build_gpt)):
        rec, _plan = _run_one(name, build)
        _check(f"{name} record closed", rec.plan_accuracy is not None)
        lo, hi = 1.0 / ACCURACY_ENVELOPE, ACCURACY_ENVELOPE
        _check(f"{name} plan_accuracy within ±25% envelope",
               lo <= rec.plan_accuracy <= hi,
               f"({rec.plan_accuracy:.3f} in [{lo:.2f}, {hi:.2f}])")

    # 2a) strict admission rejects a deliberately over-budget program
    #     BEFORE compile, naming the high-water op
    set_flags({"device_peaks": "hbm_bytes=4096",
               "memory_budget_check": "strict"})
    static.global_scope().clear()
    main_p, startup = static.Program(), static.Program()
    with static.program_guard(main_p, startup):
        feeds, fetch = build_gpt()
        exe = static.Executor()
        exe.run_startup()
        try:
            exe.run(feed=feeds, fetch_list=[fetch])
            _check("strict rejects over-budget program", False)
        except MemoryBudgetError as e:
            _check("strict rejects over-budget program",
                   e.op_index is not None and e.op_type is not None
                   and str(e.op_type) in str(e),
                   f"(high-water op #{e.op_index} <{e.op_type}>)")
            _check("rejection precedes compile", len(exe._cache) == 0)
    set_flags({"device_peaks": "", "memory_budget_check": "strict"})

    # 2b) donation-safety golden: donated-then-read rejected by name
    p = static.Program()
    b = p.global_block()
    b.create_var(name="v", shape=[8], dtype="float32", is_data=True)
    b.create_var(name="w", shape=[8], dtype="float32")
    b.create_var(name="z", shape=[8], dtype="float32")
    b.append_op("relu", {"X": ["v"]}, {"Out": ["w"]},
                {"__inplace__": ["v"]})
    b.append_op("tanh", {"X": ["v"]}, {"Out": ["z"]}, {})
    exe = static.Executor()
    try:
        exe.run(p, feed={"v": np.ones(8, "f")}, fetch_list=["z"])
        _check("strict rejects donated-then-read", False)
    except DonationError as e:
        _check("strict rejects donated-then-read",
               e.var == "v" and "use-after-donation" in str(e),
               f"(op #{e.op_index} <{e.op_type}> var {e.var!r})")
    set_flags({"memory_budget_check": "warn"})

    # 3) steady-state dispatch overhead < 1% (bench sub-row)
    import bench

    row = bench.bench_executor_dispatch(iters=150)
    sub = row["memplan"]
    _check("dispatch overhead < 1%", sub["within_target"],
           f"({sub['overhead_pct']}% of {sub['dispatch_period_us']}us; "
           f"cached check {sub['cached_check_us']}us, full plan "
           f"{sub['full_plan_us']}us)")
    _check("bench sub-row carries plan_accuracy",
           sub["plan_accuracy"] is not None,
           f"({sub['plan_accuracy']})")

    print("[memplan-smoke] PASS")


if __name__ == "__main__":
    main()
