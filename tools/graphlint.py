#!/usr/bin/env python
"""graphlint: framework-aware source lint gate (ISSUE 13).

Runs :mod:`paddle_tpu.analysis.lint` over the tree and reconciles the
findings with the committed waiver file. Pure AST — never imports jax —
so it runs first in CI before any test process starts.

Usage:
    python tools/graphlint.py                     # lint paddle_tpu/ + tools
    python tools/graphlint.py path/to/file.py     # lint specific paths
    python tools/graphlint.py --check             # CI gate: nonzero exit on
                                                  #   any unwaived finding
                                                  #   (also on unused or
                                                  #   unjustified waivers)
    python tools/graphlint.py --list-rules        # rule table
    python tools/graphlint.py --json              # machine-readable output

Waivers: tools/graphlint_waivers.txt — `<path> <rule> <scope>  # why`.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_by_path(name, relpath):
    """Import an analysis module by FILE PATH, bypassing the paddle_tpu
    package __init__ (which imports jax): the lint gate must run in a
    bare-python CI stage and never pay the framework import."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # dataclasses resolves __module__ through here
    spec.loader.exec_module(mod)
    return mod


_lint = _load_by_path("graphlint_lint", "paddle_tpu/analysis/lint.py")
_waivers = _load_by_path("graphlint_waivers", "paddle_tpu/analysis/waivers.py")
lint_paths, lint_rules = _lint.lint_paths, _lint.lint_rules
WaiverFormatError = _waivers.WaiverFormatError
load_waivers, match_waiver = _waivers.load_waivers, _waivers.match_waiver

DEFAULT_PATHS = ["paddle_tpu", "tools"]
DEFAULT_WAIVERS = os.path.join(_REPO, "tools", "graphlint_waivers.txt")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: paddle_tpu/ + "
                         "tools/; stale-waiver enforcement applies only "
                         "to this default full-scope run)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any unwaived finding (CI gate)")
    ap.add_argument("--waivers", default=DEFAULT_WAIVERS,
                    help="waiver file (default: tools/graphlint_waivers.txt)")
    ap.add_argument("--no-waivers", action="store_true",
                    help="ignore the waiver file (show every finding)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--list-rules", action="store_true")
    ns = ap.parse_args(argv)

    if ns.list_rules:
        for slug, (rid, desc, hint) in sorted(lint_rules().items(),
                                              key=lambda kv: kv[1][0]):
            print(f"{rid}  {slug}\n    {desc}\n    fix: {hint}")
        return 0

    paths = ns.paths or [os.path.join(_REPO, p) for p in DEFAULT_PATHS]
    for p in paths:
        # a typo'd path must fail loud, not silently gate nothing
        if not os.path.exists(p):
            print(f"graphlint: no such path: {p}", file=sys.stderr)
            return 2
        if os.path.isfile(p) and not p.endswith(".py"):
            print(f"graphlint: not a python file: {p}", file=sys.stderr)
            return 2
    findings = lint_paths(paths)
    # report repo-relative paths so waivers and CI logs are stable
    for f in findings:
        ap_path = os.path.abspath(f.path)
        if ap_path.startswith(_REPO + os.sep):
            f.path = os.path.relpath(ap_path, _REPO)

    try:
        waivers = [] if ns.no_waivers else load_waivers(ns.waivers)
    except WaiverFormatError as e:
        print(f"graphlint: bad waiver file: {e}", file=sys.stderr)
        return 2

    open_findings, waived = [], []
    for f in findings:
        if match_waiver(waivers, f) is not None:
            waived.append(f)
        else:
            open_findings.append(f)
    # waiver staleness is only meaningful on a full default-scope run: a
    # path-scoped invocation (pre-commit on changed files) legitimately
    # never touches most waivers and must not fail on them
    unused = [] if ns.paths else [w for w in waivers if not w.used]

    if ns.as_json:
        print(json.dumps({
            "findings": [vars(f) for f in open_findings],
            "waived": [vars(f) for f in waived],
            "unused_waivers": [vars(w) for w in unused],
        }, indent=1))
    else:
        for f in open_findings:
            print(f)
        if waived:
            print(f"graphlint: {len(waived)} finding(s) waived "
                  f"({ns.waivers})")
        for w in unused:
            print(f"graphlint: UNUSED waiver {ns.waivers}:{w.line_no}: {w}")
        if not open_findings:
            print(f"graphlint: clean ({len(findings)} finding(s) total, "
                  f"{len(waived)} waived)")
        else:
            print(f"graphlint: {len(open_findings)} unwaived finding(s)")

    if ns.check and (open_findings or unused):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
