"""Goodput-ledger smoke: the conservation + restart-continuity contract
exercised by a REAL kill -9.

Drives tests/fixtures/goodput_trainer.py (checkpointing trainer with a
controlled phase mix) through two runs:

  run 1  uninterrupted — asserts the steady-state contract: goodput >=
         0.8, phase seconds sum to measured wall within 2%
         (conservation), zero lost work, a published GOODPUT.json
         sidecar, and a parseable [monitor:goodput] line.
  run 2  FLAGS_fault_injection kills the process -9 INSIDE the 2nd
         checkpoint save (the torn-save window), then a relaunch
         resumes from the last intact snapshot — asserts the ledger
         CONTINUED: sidecar loaded, lifetime wall > post-restart wall,
         the recomputed steps charged to lost_work (not compute),
         lost_work > 0, lifetime totals monotone across the resume, and
         conservation still within 2% on the chaos run.

Wired into `make goodput-smoke` and tools/build_and_test.sh check.
"""
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "goodput_trainer.py")

GOODPUT_LINE = re.compile(
    r"\[monitor:goodput\] wall_s=[\d.]+ goodput=[\d.eE+-]+ "
    r"compute_s=[\d.]+ input_wait_s=[\d.]+ compile_s=[\d.]+ "
    r"checkpoint_s=[\d.]+ restore_s=[\d.]+ renegotiate_s=[\d.]+ "
    r"lost_work_s=[\d.]+ aborted_s=[\d.]+ idle_s=[\d.]+ "
    r"steps=\d+ lost_steps=\d+ resumes=\d+")


def run_fixture(root, extra_env=None, expect_kill=False, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["GOODPUT_CKPT_DIR"] = os.path.join(root, "ckpt")
    env["FLAGS_goodput_dir"] = os.path.join(root, "goodput")
    # publish the sidecar on every commit: the kill window is one step
    env["FLAGS_goodput_publish_interval_s"] = "0"
    env.update(extra_env or {})
    os.makedirs(env["GOODPUT_CKPT_DIR"], exist_ok=True)
    p = subprocess.run([sys.executable, FIXTURE], env=env,
                       capture_output=True, text=True, timeout=timeout)
    if expect_kill:
        assert p.returncode == -9, (
            f"expected SIGKILL death, got rc={p.returncode}\n"
            f"{p.stderr[-2000:]}")
        return None
    assert p.returncode == 0, p.stderr[-3000:]
    line = [l for l in p.stdout.strip().splitlines()
            if l.startswith("{")][-1]
    return json.loads(line)


def check_conservation(out, label):
    err = float(out["conservation_error"])
    assert err <= 0.02, (
        f"[{label}] phases overrun wall by {err:.1%} (> 2%): "
        f"{out['phases']}")
    total = sum(out["phases"].values())
    assert abs(total - out["wall_s"]) <= 0.02 * out["wall_s"] + 1e-6, (
        f"[{label}] phase sum {total:.3f}s != wall {out['wall_s']:.3f}s")
    print(f"[goodput-smoke] {label}: wall={out['wall_s']:.2f}s "
          f"goodput={out['goodput']:.3f} conservation_err={err:.4f}")


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = tempfile.mkdtemp(prefix="ptpu_goodput_")
    try:
        # -- run 1: uninterrupted steady state ---------------------------
        d1 = os.path.join(root, "clean")
        out = run_fixture(d1)
        check_conservation(out, "run1")
        assert out["goodput"] >= 0.8, (
            f"steady-state goodput {out['goodput']:.3f} < 0.8: "
            f"{out['phases']}")
        assert out["lost_steps"] == 0 and out["resumes"] == 0, out
        assert out["phases"]["compute"] > 0
        assert out["phases"]["input_wait"] > 0, (
            "input-wait feed never reached the ledger", out["phases"])
        assert out["phases"]["checkpoint"] > 0, (
            "sync checkpoint saves left no checkpoint seconds",
            out["phases"])
        sidecar = os.path.join(d1, "goodput", "GOODPUT.json")
        assert os.path.isfile(sidecar), "sidecar never published"
        glines = [l for l in out["monitor_lines"]
                  if l.startswith("[monitor:goodput]")]
        assert glines and all(GOODPUT_LINE.match(l) for l in glines), (
            "goodput line missing or unparseable", glines)
        print(f"[goodput-smoke] run1: {len(glines)} parseable "
              "[monitor:goodput] lines, sidecar published")

        # -- run 2: kill -9 inside the 2nd save, then resume -------------
        d2 = os.path.join(root, "chaos")
        run_fixture(d2, expect_kill=True, extra_env={
            "FLAGS_fault_injection": "kill:point=mid_save,n=2"})
        assert os.path.isfile(os.path.join(d2, "goodput", "GOODPUT.json")), (
            "kill run died before any sidecar publication")
        pre = json.load(open(os.path.join(d2, "goodput", "GOODPUT.json")))
        pre_life_wall = float(pre["body"]["wall_s"])
        pre_steps = int(pre["body"]["steps"])
        print(f"[goodput-smoke] run2: killed -9 mid-save; sidecar holds "
              f"{pre_steps} steps / {pre_life_wall:.2f}s")

        out2 = run_fixture(d2)
        check_conservation(out2, "run2-resume")
        assert out2["resumed_from"] >= 0 and out2["sidecar_loaded"], out2
        assert out2["resumes"] == 1, out2
        # the ledger CONTINUED: lifetime accounting spans both lives
        life = out2["lifetime"]
        assert life["wall_s"] > out2["wall_s"], (
            "lifetime wall did not extend past the post-restart wall",
            life["wall_s"], out2["wall_s"])
        assert life["wall_s"] >= pre_life_wall, "lifetime wall regressed"
        assert life["steps"] >= pre_steps + out2["steps_run"] - \
            out2["lost_steps"] - 1 or life["steps"] > pre_steps, (
            "lifetime steps not monotone", life, pre_steps)
        # recomputation landed in lost_work, NOT compute: exactly the
        # steps committed after the manifest the resume loaded
        expected_lost = out2["max_committed_step"] - out2["resumed_from"]
        assert out2["lost_steps"] >= 1, out2
        assert out2["phases"]["lost_work"] > 0, out2["phases"]
        assert out2["lost_work_priced_s"] > 0, out2
        assert out2["lost_steps"] <= expected_lost, (
            "more lost steps than the recompute window", out2)
        print(f"[goodput-smoke] run2-resume: resumed_from="
              f"{out2['resumed_from']} lost_steps={out2['lost_steps']} "
              f"lost_work_s={out2['phases']['lost_work']:.3f} "
              f"priced={out2['lost_work_priced_s']:.3f}s "
              f"lifetime_wall={life['wall_s']:.2f}s")
        print("[goodput-smoke] PASS: goodput >= 0.8 steady-state, 2% "
              "conservation on both runs, kill -9 resume continued the "
              "lifetime ledger with recomputation charged to lost_work")
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
