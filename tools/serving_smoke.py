#!/usr/bin/env python
"""CI smoke for the online serving subsystem (`make serve-smoke`).

Stands up the full stack — LeNet exported through ``jit.save``, loaded
into an inference ``Predictor``, served by ``InferenceServer`` (dynamic
batcher + replica pool + HTTP frontend) — and asserts the production
contracts end to end:

- readiness gating: ``/healthz`` is 503 until every batch bucket is
  warmed, 200 after;
- bounded compiles: warmup + a burst of mixed-size requests cost exactly
  ``len(buckets)`` jit-cache misses (profiler counters);
- correctness: batched-and-padded responses match direct
  ``Predictor.run`` results;
- backpressure: a full admission queue answers 429, not unbounded
  queueing;
- graceful drain: ``stop(drain=True)`` completes in-flight work, kills
  the workers, and closes the listener.

Exit 0 on success; nothing here depends on wall-clock timing beyond
generous waits — a failure is a real serving regression.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
from urllib.error import HTTPError, URLError
from urllib.request import Request, urlopen

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

BUCKETS = (1, 2, 4)
QUEUE_CAPACITY = 4


def _post(url, payload):
    body = json.dumps(payload).encode()
    try:
        r = urlopen(Request(url + "/predict", data=body,
                            headers={"Content-Type": "application/json"}))
        return r.status, json.loads(r.read())
    except HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def main():
    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.models import LeNet
    from paddle_tpu.serving import InferenceServer

    paddle.seed(0)
    net = LeNet()
    model_dir = tempfile.mkdtemp(prefix="ptpu_serve_smoke_")
    paddle.jit.save(net, model_dir,
                    input_spec=[paddle.jit.InputSpec([None, 1, 28, 28])])
    pred = create_predictor(Config(model_dir))

    # reference results from a SEPARATE predictor (its own Executor, so
    # its compiles don't pre-warm the serving cache and the bounded-
    # compile accounting below stays exact)
    pred_ref = create_predictor(Config(model_dir))
    rng = np.random.RandomState(0)
    sizes = [1, 2, 3, 1, 2, 3]
    refs = []
    for i, rows in enumerate(sizes):
        a = rng.randn(rows, 1, 28, 28).astype("float32")
        refs.append((a, np.asarray(pred_ref.run([a])[0])))

    srv = InferenceServer(pred, port=0, replicas=2, buckets=BUCKETS,
                          queue_capacity=QUEUE_CAPACITY,
                          batch_timeout_ms=1.0)
    try:
        # -- readiness gating ------------------------------------------
        srv.start(warmup=False)
        try:
            urlopen(srv.url + "/healthz")
            raise AssertionError("/healthz must be 503 before warmup")
        except HTTPError as e:
            assert e.code == 503, e.code

        misses0 = profiler.counters().get("executor::jit_cache_miss", 0)
        srv.warmup()
        warm_misses = (profiler.counters().get("executor::jit_cache_miss",
                                               0) - misses0)
        assert warm_misses == len(BUCKETS), (
            f"warmup cost {warm_misses} compiles, expected {len(BUCKETS)}")
        hz = json.loads(urlopen(srv.url + "/healthz").read())
        assert hz["ready"] and hz["warmed"], hz

        # -- mixed-size requests: 200s + padding-parity ----------------
        for a, ref in refs:
            status, out = _post(srv.url, {"inputs": a.tolist()})
            assert status == 200, (status, out)
            got = np.asarray(next(iter(out["outputs"].values())),
                             dtype="float32")
            np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
        total = (profiler.counters().get("executor::jit_cache_miss", 0)
                 - misses0)
        assert total == len(BUCKETS), (
            f"mixed traffic grew compiles to {total}; the bucket ladder "
            "must bound them")
        assert srv.pool.extra_compiles() == 0

        # -- 429 backpressure under a full queue -----------------------
        srv.pool.pause()
        feed = srv.feed_names[0]
        parked = [srv.batcher.submit(
            {feed: np.zeros((1, 1, 28, 28), "float32")})
            for _ in range(QUEUE_CAPACITY)]
        status, out = _post(
            srv.url, {"inputs": np.zeros((1, 1, 28, 28)).tolist()})
        assert status == 429, (status, out)
        srv.pool.resume()
        for req in parked:  # queued work completes after resume
            assert len(req.wait(timeout=30)) >= 1
        sz = json.loads(urlopen(srv.url + "/statz").read())
        assert sz["requests"]["rejected_429"] >= 1, sz["requests"]

        # -- clean drain ----------------------------------------------
        results = []

        def client():
            a = np.zeros((2, 1, 28, 28), "float32")
            results.append(_post(srv.url, {"inputs": a.tolist()})[0])

        threads = [threading.Thread(target=client) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == [200, 200, 200], results
        srv.stop(drain=True)
        assert srv.pool.alive == 0, "replica workers survived drain"
        try:
            urlopen(srv.url + "/healthz", timeout=2)
            raise AssertionError("listener still up after stop()")
        except (URLError, ConnectionError, OSError):
            pass
        print(f"serve-smoke OK: {len(BUCKETS)} buckets = {total} compiles, "
              f"{sz['requests']['completed']} served, mean fill "
              f"{sz['batches']['mean_fill']}, 429 + drain verified")
        return 0
    finally:
        srv.stop(drain=False)


if __name__ == "__main__":
    sys.exit(main())
