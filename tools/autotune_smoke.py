#!/usr/bin/env python
"""CI smoke for the kernel autotuner (`make autotune-smoke`).

Asserts the four contracts the tuning subsystem rests on, end to end
on the CPU backend (pallas interpret mode drives the real search
pipeline; timings are real wall clock, selection logic is identical to
TPU):

1. **Fused-vs-jnp parity** — the layernorm_residual and conv+bn+relu
   pallas kernels match their unfused jnp references, INCLUDING under
   the non-default schedules the tuner may pick.
2. **Offline search works** — tuning the two kernels measures the
   default point, prunes invalid candidates before any compile, and
   records a winner in the versioned JSON cache next to
   FLAGS_persistent_compile_cache_dir.
3. **Warm cache = zero search** — a FRESH process pointed at the same
   cache dir resolves the tuned schedules with autotune::search == 0
   and autotune::cache_hit > 0 (the steady-state-pays-nothing
   contract), and the resolved params equal the parent's winners.
4. **Corruption degrades, never crashes** — a truncated cache file in
   a fresh process still resolves (defaults), with the
   autotune::cache_reject counter bumped exactly once.

Exit 0 on success.
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

LN_INFO = dict(rows=128, h=256, dtype="float32")
CBR_INFO = dict(m=256, k=64, c=128, dtype="float32")


def _parity():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import pallas as _pk  # noqa: F401 (bind modules)

    lnr = sys.modules["paddle_tpu.ops.pallas.layernorm_residual"]
    cbr = sys.modules["paddle_tpu.ops.pallas.conv_bn_relu"]

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(37, 256).astype("f4"))
    r = jnp.asarray(rng.randn(37, 256).astype("f4"))
    w = jnp.asarray(rng.randn(256).astype("f4"))
    b = jnp.asarray(rng.randn(256).astype("f4"))
    ref = lnr._reference(x, r, w, b, 1e-5)
    for block_r in (8, 32, 256):  # schedules the tuner may pick
        y, _, _ = lnr._pallas_fwd(x, r, w, b, 1e-5, interpret=True,
                                  block_r=block_r)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(y),
                                   rtol=1e-5, atol=1e-5)

    xc = jnp.asarray(rng.randn(2, 3, 10, 10).astype("f4"))
    wc = jnp.asarray(rng.randn(8, 3, 3, 3).astype("f4") * 0.2)
    gamma = jnp.asarray(rng.rand(8).astype("f4") + 0.5)
    beta = jnp.asarray(rng.randn(8).astype("f4") * 0.1)
    mean = jnp.asarray(rng.randn(8).astype("f4") * 0.1)
    var = jnp.asarray(rng.rand(8).astype("f4") + 0.5)
    for training in (True, False):
        kw = dict(stride=2, padding=1, training=training, momentum=0.9,
                  eps=1e-5, data_format="NCHW")
        ry, rm, rv = cbr._reference(xc, wc, gamma, beta, mean, var, **kw)
        fy, fm, fv = cbr._fused(xc, wc, gamma, beta, mean, var,
                                interpret=True, force=True, **kw)
        np.testing.assert_allclose(np.asarray(ry), np.asarray(fy),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(rm), np.asarray(fm),
                                   rtol=1e-4, atol=1e-5)
        # backward through the fused kernels vs autodiff of the chain
        gr = jax.grad(lambda *a: (cbr._reference(*a, mean, var, **kw)[0]
                                  ** 2).sum(), argnums=(0, 1, 2, 3))(
            xc, wc, gamma, beta)
        gf = jax.grad(lambda *a: (cbr._fused(*a, mean, var,
                                             interpret=True, force=True,
                                             **kw)[0] ** 2).sum(),
                      argnums=(0, 1, 2, 3))(xc, wc, gamma, beta)
        for a, b_ in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-3, atol=2e-4)
    print("parity OK (layernorm + conv_bn_relu, pallas == jnp, "
          "default AND tuned schedules, fwd + bwd)")


def _tune_and_persist(cache_dir):
    from paddle_tpu import profiler, tuning
    from paddle_tpu.flags import set_flags

    set_flags({"persistent_compile_cache_dir": cache_dir,
               "kernel_autotune": "search"})
    tuner = tuning.KernelTuner(measure_n=2)
    winners = {}
    res = tuner.tune("layernorm_residual",
                     candidates=[{"block_r": 8}, {"block_r": 32},
                                 {"block_r": 4096}],  # last one prunes
                     **LN_INFO)
    assert res.pruned == 1, res  # VMEM predicate fired BEFORE compile
    assert res.default_us is not None  # the baseline was measured
    winners["layernorm_residual"] = res.params
    res = tuner.tune("conv_bn_relu",
                     candidates=[{"tile_m": 64}, {"tile_m": 128}],
                     **CBR_INFO)
    winners["conv_bn_relu"] = res.params
    path = os.path.join(cache_dir, tuning.CACHE_FILE_NAME)
    assert os.path.exists(path), "tuning cache file not written"
    with open(path) as f:
        raw = json.load(f)
    assert raw["schema"] == tuning.CACHE_SCHEMA_VERSION
    assert len(raw["entries"]) == 2
    # the winners resolve immediately in THIS process too
    assert tuning.resolve("layernorm_residual", **LN_INFO) \
        == winners["layernorm_residual"]
    c = profiler.counters()
    assert c.get("autotune::search", 0) == 2, c
    print(f"offline search OK: 2 kernels tuned, winners {winners}, "
          f"cache at {path}")
    return winners


_CHILD = r"""
import json, os, sys
sys.path.insert(0, {root!r})
import paddle_tpu
from paddle_tpu import profiler, tuning

ln = tuning.resolve("layernorm_residual", **{ln_info!r})
cbr = tuning.resolve("conv_bn_relu", **{cbr_info!r})
c = profiler.counters()
print(json.dumps({{
    "layernorm_residual": ln,
    "conv_bn_relu": cbr,
    "search": c.get("autotune::search", 0),
    "enqueued": c.get("autotune::enqueued", 0),
    "cache_hit": c.get("autotune::cache_hit", 0),
    "cache_reject": c.get("autotune::cache_reject", 0),
    "pending": tuning.pending_searches(),
}}))
"""


def _fresh_process(cache_dir, extra_env=None):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               FLAGS_persistent_compile_cache_dir=cache_dir,
               FLAGS_kernel_autotune="search")
    env.update(extra_env or {})
    code = _CHILD.format(root=root, ln_info=LN_INFO, cbr_info=CBR_INFO)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, (out.stdout, out.stderr)
    return json.loads(out.stdout.strip().splitlines()[-1])


def _warm_cache_zero_search(cache_dir, winners):
    got = _fresh_process(cache_dir)
    # the tuned winners crossed the process boundary...
    assert got["layernorm_residual"] == winners["layernorm_residual"], got
    assert got["conv_bn_relu"] == winners["conv_bn_relu"], got
    # ...and steady state paid ZERO search (mode=search, but every
    # resolve was a cache hit: nothing to enqueue, nothing to measure)
    assert got["search"] == 0, got
    assert got["enqueued"] == 0 and got["pending"] == 0, got
    assert got["cache_hit"] >= 2, got
    print("warm-cache round trip OK: fresh process resolved both tuned "
          "schedules with zero re-search")


def _corrupt_cache_degrades(cache_dir):
    from paddle_tpu import tuning

    path = os.path.join(cache_dir, tuning.CACHE_FILE_NAME)
    with open(path, "w") as f:
        f.write('{"schema": 1, "entries": {"torn')
    got = _fresh_process(cache_dir)
    # defaults, one file-level reject, no crash (exit 0 got us here)
    ln_default = tuning.schedule_space("layernorm_residual") \
        .default_params(LN_INFO)
    assert got["layernorm_residual"] == ln_default, got
    assert got["cache_reject"] == 1, got
    print("corrupt-cache OK: truncated file degraded to defaults with "
          "one cache_reject, no crash")


def main():
    _parity()
    cache_dir = tempfile.mkdtemp(prefix="ptpu_autotune_smoke_")
    try:
        winners = _tune_and_persist(cache_dir)
        _warm_cache_zero_search(cache_dir, winners)
        _corrupt_cache_degrades(cache_dir)
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    print("autotune smoke OK")


if __name__ == "__main__":
    main()
