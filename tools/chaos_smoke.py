"""Chaos smoke: every elastic-recovery path exercised by a REAL kill -9.

Drives tests/fixtures/dist_elastic.py (checkpoint-every-step ZeRO-1
trainer) through a preemption story on one host:

  ref     uninterrupted run, 4 virtual devices, steps 0..7 — the truth
  phase1  fresh job, 4 devices, FLAGS_fault_injection kills the process
          INSIDE the 3rd checkpoint save (after data files, before the
          manifest) — the torn-save window
  phase2  2 devices (the world SHRANK), resumes from the last intact
          snapshot (reshard 4→2), killed -9 again at a step boundary
  phase3  4 devices (the world GREW back), resumes resharded 2→4 and
          completes — its recomputed losses must match ref exactly

Asserts: every kill really died by SIGKILL; a torn .tmp never loads and
is swept; resume always lands on an intact snapshot; the final run
reports reshards >= 1, dp-sharded ZeRO-1 accumulators, and a
loss-curve-identical continuation. Wired into `make chaos-smoke` and
tools/build_and_test.sh check.
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "dist_elastic.py")


def run_fixture(ckpt_dir, devices, extra_env=None, expect_kill=False,
                timeout=240):
    sys.path.insert(0, REPO)
    from paddle_tpu.distributed.launch import _build_env, _free_port

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices}"
    env["JAX_ENABLE_X64"] = "true"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_CKPT_DIR"] = ckpt_dir
    env["ELASTIC_TOTAL_STEPS"] = "8"
    env.update(extra_env or {})
    env = _build_env(0, 1, f"127.0.0.1:{_free_port()}", env)
    p = subprocess.run([sys.executable, FIXTURE], env=env,
                       capture_output=True, text=True, timeout=timeout)
    if expect_kill:
        assert p.returncode == -9, (
            f"expected SIGKILL death, got rc={p.returncode}\n"
            f"{p.stderr[-2000:]}")
        return None
    assert p.returncode == 0, p.stderr[-3000:]
    line = [l for l in p.stdout.strip().splitlines()
            if l.startswith("{")][-1]
    return json.loads(line)


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = tempfile.mkdtemp(prefix="ptpu_chaos_")
    try:
        # -- reference: the uninterrupted loss curve ----------------------
        ref = run_fixture(os.path.join(root, "ref"), devices=4)
        ref_losses = {int(k): v for k, v in ref["losses"].items()}
        assert sorted(ref_losses) == list(range(8)), ref
        assert ref["zero1_dp_sharded"], "ZeRO-1 accums not dp-sharded"
        print(f"[chaos-smoke] ref: 8 steps, final loss "
              f"{ref_losses[7]:.6f}")

        chaos_dir = os.path.join(root, "chaos")

        # -- phase 1: kill -9 INSIDE the 3rd save (torn-save window) ------
        run_fixture(chaos_dir, devices=4, expect_kill=True, extra_env={
            "FLAGS_fault_injection": "kill:point=mid_save,n=3"})
        from paddle_tpu.distributed import checkpoint as ckpt

        torn = [d for d in os.listdir(chaos_dir) if d.endswith(".tmp")]
        assert torn, "mid-save kill left no torn .tmp dir?"
        path, manifest = ckpt.latest_checkpoint(chaos_dir)
        assert path is not None, "no intact snapshot survived phase 1"
        assert manifest["step"] < 7
        print(f"[chaos-smoke] phase1: killed mid-save; torn={torn}, "
              f"newest intact snapshot step {manifest['step']}")

        # -- phase 2: world shrinks 4->2 devices, killed at a step -------
        # the delay directive (straggler emulation) fires first at the
        # same boundary, letting the async writer flush its queue, THEN
        # the kill lands — so phase 3 provably resumes from a snapshot
        # this 2-device world wrote
        run_fixture(chaos_dir, devices=2, expect_kill=True, extra_env={
            "FLAGS_fault_injection":
                "delay:point=step,step=6,ms=600;kill:point=step,step=6"})
        path2, man2 = ckpt.latest_checkpoint(chaos_dir)
        assert man2["step"] > manifest["step"], (
            "phase 2 published no snapshots of its own", man2)
        assert man2["mesh_shape"]["dp"] == 2
        print(f"[chaos-smoke] phase2: resumed at world size 2, killed -9 "
              f"at step 6; newest intact snapshot step {man2['step']}")

        # -- phase 3: world grows back to 4, runs to completion ----------
        out = run_fixture(chaos_dir, devices=4)
        assert out["resumed_from"] >= 0, out
        assert out["reshards"] >= 1, (
            "2-device snapshot restored onto the 4-device mesh without "
            f"a reshard? {out}")
        assert out["zero1_dp_sharded"], out
        assert out["steps"] and out["steps"][-1] == 7, out
        leftover = [d for d in os.listdir(chaos_dir)
                    if d.endswith(".tmp")]
        assert not leftover, f"torn tmps not swept: {leftover}"

        # -- the acceptance: loss-curve-identical continuation -----------
        import numpy as np

        for s, v in sorted((int(k), v) for k, v in out["losses"].items()):
            np.testing.assert_allclose(
                v, ref_losses[s], rtol=5e-4, atol=1e-6,
                err_msg=f"step {s} diverged after kill -9 + reshard")
        print(f"[chaos-smoke] phase3: resumed from step "
              f"{out['resumed_from']} resharded onto 4 devices; steps "
              f"{out['steps'][0]}..{out['steps'][-1]} match the "
              "uninterrupted curve")
        print("[chaos-smoke] PASS: kill -9 mid-save + two world resizes "
              "recovered with an identical loss curve")
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
