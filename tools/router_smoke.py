#!/usr/bin/env python
"""CI smoke for the serving fleet tier (`make router-smoke`).

Boots the REAL fleet shape — two independent backend processes
(``python -m paddle_tpu.serving.backend`` via the scaler's
SubprocessLauncher) behind a Router — and asserts the availability
contracts a load balancer exists for:

- fleet readiness: both backends admitted, per-backend ``/loadz``
  compile accounting exact (warmup == len(buckets) jit misses, zero
  unexpected);
- **kill -9 survival**: one backend is SIGKILLed mid-burst and every
  client request still answers 200 — connection failures retry on the
  survivor, the dead backend's eviction counter bumps, and no client
  ever sees the failure;
- fleet introspection: /statz shows the surviving backend and merged
  latency quantiles;
- clean teardown: graceful terminate of the survivor (SIGTERM -> drain
  -> exit 0), router drain, and NOTHING left alive — no processes, no
  listeners.

Exit 0 on success; a failure is a real fleet regression.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import threading
import time
from urllib.error import HTTPError, URLError
from urllib.request import Request, urlopen

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

BUCKETS = (1, 2, 4)
IN_DIM = 16
CLIENTS = 6
PER_CLIENT = 20


def _build_model_dir():
    import paddle_tpu.static as static

    static.enable_static()
    static.reset_default_programs()
    static.global_scope().clear()
    try:
        x = static.data("x", [None, IN_DIM], "float32")
        h = static.nn.fc(x, 64, name="rsm_fc1")
        y = static.nn.fc(h, 8, name="rsm_fc2")
        exe = static.Executor()
        exe.run_startup()
        d = tempfile.mkdtemp(prefix="ptpu_router_smoke_")
        static.save_inference_model(d, ["x"], [y], exe)
    finally:
        static.disable_static()
        static.reset_default_programs()
    return d


def _post(url, rows, timeout=30):
    a = np.random.RandomState(rows).randn(rows, IN_DIM).astype("float32")
    body = json.dumps({"inputs": a.tolist()}).encode()
    try:
        r = urlopen(Request(url + "/predict", data=body,
                            headers={"Content-Type": "application/json"}),
                    timeout=timeout)
        return r.status
    except HTTPError as e:
        return e.code
    except (URLError, ConnectionError, OSError) as e:
        # a dropped connection is ALSO a client-visible failure — it
        # must fail the zero-failures assertion, not kill the thread
        return f"conn: {type(e).__name__}"


def main():
    from paddle_tpu.serving import Router, SubprocessLauncher

    model_dir = _build_model_dir()
    launcher = SubprocessLauncher(
        model_dir, buckets=BUCKETS, batch_timeout_ms=1.0,
        queue_capacity=256)
    print("booting 2 backend processes ...", flush=True)
    handles = [launcher.launch(), launcher.launch()]
    # probe on a long interval: the kill-recovery below must happen via
    # the DISPATCH path (connect failure -> evict -> retry), not get
    # cleaned up early by a lucky probe
    router = Router(backends=[h.url for h in handles],
                    probe_interval_s=5.0).start()
    try:
        assert router.healthy_count == 2, router.healthz()
        for h in handles:
            lz = json.loads(urlopen(h.url + "/loadz").read())
            assert lz["ready"] and lz["kind"] == "predict", lz
            assert lz["compiles"]["jit_misses"] == len(BUCKETS), lz
            assert lz["compiles"]["unexpected"] == 0, lz
        print(f"fleet ready: 2 backends x {len(BUCKETS)} warmup "
              "compiles each, 0 unexpected", flush=True)

        # -- kill -9 one backend mid-burst -----------------------------
        statuses = []
        done = [0]
        lock = threading.Lock()

        def client(cid):
            for i in range(PER_CLIENT):
                s = _post(router.url, rows=(i % 3) + 1)
                with lock:
                    statuses.append(s)
                    done[0] += 1

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(CLIENTS)]
        for t in threads:
            t.start()
        while True:  # kill once the burst is genuinely in flight
            with lock:
                if done[0] >= (CLIENTS * PER_CLIENT) // 4:
                    break
            time.sleep(0.002)
        victim = handles[0]
        os.kill(victim.proc.pid, signal.SIGKILL)
        print(f"kill -9 backend {victim.url} mid-burst "
              f"(after {done[0]} requests)", flush=True)
        for t in threads:
            t.join()
        victim.proc.wait(10)

        assert len(statuses) == CLIENTS * PER_CLIENT, (
            f"only {len(statuses)}/{CLIENTS * PER_CLIENT} requests "
            "accounted for — a client thread died")
        failed = [s for s in statuses if s != 200]
        assert not failed, (
            f"{len(failed)} requests failed after the kill: "
            f"{sorted(set(failed))} — retry-to-survivor must make the "
            "kill invisible to clients")
        sz = router.statz()
        assert sz["fleet"]["evictions"] >= 1, sz["fleet"]
        assert sz["fleet"]["retries"] >= 1, sz["fleet"]
        assert sz["backends_healthy"] == 1, sz
        merged = sz["latency"]["backends_merged"]
        assert merged.get("serving/e2e_ms", {}).get("count", 0) > 0, (
            "merged fleet quantiles missing", merged)
        print(f"burst OK: {len(statuses)} requests all 200 "
              f"(evictions={sz['fleet']['evictions']}, "
              f"retries={sz['fleet']['retries']}), survivor p99 "
              f"{merged['serving/e2e_ms']['p99_ms']}ms", flush=True)

        # -- clean teardown --------------------------------------------
        launcher.terminate(handles[1], drain=True)
        assert handles[1].proc.returncode == 0, (
            f"graceful drain must exit 0, got "
            f"{handles[1].proc.returncode}")
        router.stop(drain=True)
        for h in handles:
            assert h.proc.poll() is not None, f"{h.url} still alive"
        try:
            urlopen(router.url + "/healthz", timeout=2)
            raise AssertionError("router listener still up after stop()")
        except (URLError, ConnectionError, OSError):
            pass
        for h in handles:
            try:
                urlopen(h.url + "/healthz", timeout=2)
                raise AssertionError(f"backend {h.url} listener still up")
            except (URLError, ConnectionError, OSError):
                pass
        print("router-smoke OK: kill -9 invisible to clients, drain "
              "left no live processes or listeners")
        return 0
    finally:
        router.stop(drain=False)
        for h in handles:
            try:
                launcher.terminate(h, drain=False, timeout_s=5)
            except Exception:
                pass


if __name__ == "__main__":
    sys.exit(main())
